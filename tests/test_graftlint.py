"""graftlint: rule unit tests + the tier-1 gate over the real tree.

Layout:
- one positive AND one negative test per rule (acceptance criterion);
- traced-scope model tests (conventions: ``apply`` traced, eager
  ``forward`` not, call-graph reachability, taint laundering);
- suppression scoping (trailing line / standalone-above / file-level);
- CLI exit codes + JSON schema;
- ``--changed-only`` filtering unit;
- THE GATE: ``bigdl_tpu/`` must be violation-free modulo reviewed
  inline suppressions.  This test is what makes graftlint part of
  tier-1 — a PR that introduces a silent-recompile / host-sync /
  impure-forward hazard fails here with rule id + file:line.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.graftlint import (
    JSON_SCHEMA_VERSION,
    all_rules,
    filter_changed,
    lint_paths,
    lint_source,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = "bigdl_tpu/nn/fake.py"  # default lint path: library, traced rules on


def lint(src, path=LIB, **kw):
    return lint_source(textwrap.dedent(src), path=path, **kw)


def rule_ids(src, path=LIB, **kw):
    return sorted({v.rule for v in lint(src, path=path, **kw)})


# ===========================================================================
# GL101 host-sync
# ===========================================================================
class TestHostSync:
    def test_positive_item_float_asarray_device_get(self):
        vs = lint("""
            import jax
            import numpy as np
            class Foo(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    v = input.sum().item()
                    f = float(input.mean())
                    a = np.asarray(input)
                    g = jax.device_get(input)
                    return v + f, state
            """)
        assert [v.rule for v in vs] == ["GL101"] * 4
        assert all(v.severity == "error" for v in vs)

    def test_negative_static_receiver_and_eager_forward(self):
        # np.asarray of a static config table is trace-time constant
        # folding; .item()/float() in the EAGER forward path are fine
        assert rule_ids("""
            import numpy as np
            class Foo(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    tbl = np.asarray(self.conn_table)
                    return input * tbl.sum().item(), state
                def forward(self, x):
                    return float(x.sum())
            """) == []

    def test_positive_reachable_through_helper(self):
        # "reachable from jitted paths": the sync lives in a helper the
        # traced apply calls — the helper's param is tainted via the
        # call site
        vs = lint("""
            def _readout(x):
                return x.max().item()
            class Foo(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return _readout(input), state
            """)
        assert [(v.rule, "_readout" in v.message) for v in vs] == \
            [("GL101", True)]

    def test_negative_helper_called_with_static_only(self):
        assert rule_ids("""
            import numpy as np
            def _lookup(name):
                return np.asarray(TABLES[name]).item()
            class Foo(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    return input * _lookup(self.kind), state
            """) == []


# ===========================================================================
# GL102 tensor-branch
# ===========================================================================
class TestTensorBranch:
    def test_positive_if_while_assert_on_tensor(self):
        vs = lint("""
            import jax.numpy as jnp
            class Foo(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    if input.sum() > 0:
                        input = -input
                    while jnp.any(input > 0):
                        input = input - 1
                    assert input.mean() < 1
                    return input, state
            """)
        assert [v.rule for v in vs] == ["GL102"] * 3
        msgs = " ".join(v.message for v in vs)
        assert "lax.cond" in msgs and "lax.while_loop" in msgs

    def test_negative_static_branches(self):
        # shape/rank dispatch, hyper-params, rng None-plumbing, dict
        # membership, training flag: all legal trace-time branches
        assert rule_ids("""
            class Foo(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    if input.ndim == 3:
                        input = input[None]
                    if rng is None and self.p > 0:
                        pass
                    if "gamma" in params:
                        input = input * params["gamma"]
                    if training and input.shape[0] > 1:
                        pass
                    return input, state
            """) == []

    def test_positive_optimizer_update(self):
        vs = lint("""
            class Clip(OptimMethod):
                def update(self, grads, params, opt_state, lr, step):
                    if grads["w"].sum() > 1e3:
                        grads = clip(grads)
                    return params, opt_state
            """, path="bigdl_tpu/optim/fake.py")
        assert [v.rule for v in vs] == ["GL102"]

    def test_negative_host_transform_not_traced(self):
        # transform/vision.py-style numpy augmentation: apply on a
        # non-Module class is host-side, branch away
        assert rule_ids("""
            class Brightness(FeatureTransformer):
                def apply(self, img):
                    if img.mean() > 0.5:
                        img = img * 0.9
                    return img
            """, path="bigdl_tpu/transform/fake.py") == []

    def test_positive_jit_decorated_function(self):
        vs = lint("""
            import jax
            @jax.jit
            def step(params, x):
                if x.sum() > 0:
                    return params
                return x
            """, path="bigdl_tpu/optim/fake.py")
        assert [v.rule for v in vs] == ["GL102"]

    def test_positive_lax_combinator_callback(self):
        vs = lint("""
            from jax import lax
            def body(carry):
                if carry > 0:
                    return carry - 1
                return carry
            def run(x):
                return lax.while_loop(lambda c: c != 0, body, x)
            """)
        assert [v.rule for v in vs] == ["GL102"]

    def test_negative_builtin_map_callback_is_host_code(self):
        # builtin map() is host iteration; only lax.map traces
        assert rule_ids("""
            def _fmt(row):
                if row > 0:
                    return "+"
                return "-"
            def report(rows):
                return list(map(_fmt, rows))
            """, path="bigdl_tpu/utils/fake.py") == []

    def test_positive_lax_map_callback_is_traced(self):
        vs = lint("""
            from jax import lax
            def _body(row):
                if row.sum() > 0:
                    return row
                return -row
            def run(xs):
                return lax.map(_body, xs)
            """)
        assert [v.rule for v in vs] == ["GL102"]

    def test_negative_scalar_annotated_config_param(self):
        # `causal: bool` under a shard_map callback is partial-bound
        # static config, not a tracer
        assert rule_ids("""
            from functools import partial
            def _local(q, k, *, causal: bool, axis_name: str):
                if causal:
                    q = q * 2
                return q
            def attn(q, k, mesh):
                return shard_map(partial(_local, causal=True,
                                         axis_name="seq"),
                                 mesh=mesh)(q, k)
            """, path="bigdl_tpu/parallel/fake.py") == []


# ===========================================================================
# GL103 impure-forward
# ===========================================================================
class TestPurity:
    def test_positive_self_mutation_and_global(self):
        vs = lint("""
            class Foo(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    self.output = input * 2
                    self.cache.append(input)
                    global _STEPS
                    _STEPS += 1
                    return input, state
            """)
        assert [v.rule for v in vs] == ["GL103"] * 3

    def test_negative_locals_and_eager_paths(self):
        # local assignment in apply is fine; eager forward/backward
        # write self by design (not traced); __init__ is never traced
        assert rule_ids("""
            class Foo(Module):
                def __init__(self):
                    self.calls = 0
                def forward(self, x):
                    self.output = x
                    return x
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    out = input * 2
                    new_state = {"mean": out.mean()}
                    return out, new_state
            """) == []

    def test_negative_functional_update_call_is_not_a_dict_write(self):
        # composing optimizers: self.inner.update(g, p, s, lr, it) is
        # the 5-arg functional contract, not container mutation
        assert rule_ids("""
            class Wrapped(OptimMethod):
                def update(self, grads, params, opt_state, lr, step):
                    return self.inner.update(grads, params, opt_state,
                                             lr, step)
            """, path="bigdl_tpu/optim/fake.py") == []

    def test_positive_closure_nonlocal(self):
        vs = lint("""
            class Foo(Module):
                def apply(self, params, state, input, *, training=False,
                          rng=None):
                    count = 0
                    def inner(x):
                        nonlocal count
                        count += 1
                        return x
                    return inner(input), state
            """)
        assert [v.rule for v in vs] == ["GL103"]


# ===========================================================================
# GL104 float64-promotion
# ===========================================================================
class TestFloat64:
    def test_positive_np_float64_and_dtype_strings(self):
        vs = lint("""
            import numpy as np
            A = np.zeros(4, dtype=np.float64)
            def f(x):
                return x.astype("float64")
            B = np.ones(3, dtype="float64")
            """)
        assert [v.rule for v in vs] == ["GL104"] * 3

    def test_negative_f32_and_nonlibrary_paths(self):
        assert rule_ids("""
            import numpy as np
            A = np.zeros(4, dtype=np.float32)
            """) == []
        src = "import numpy as np\nA = np.float64(3)\n"
        assert rule_ids(src, path="tests/test_foo.py") == []
        assert rule_ids(src, path="bigdl_tpu/dataset/foo.py") == []
        # interop/ is the wire-format boundary: f64 mandated there
        assert rule_ids(src, path="bigdl_tpu/interop/foo.py") == []


# ===========================================================================
# GL105 nondeterministic-rng
# ===========================================================================
class TestNpRandom:
    def test_positive_global_rng_and_unseeded_generator(self):
        vs = lint("""
            import numpy as np
            def init(shape):
                return np.random.normal(0, 1, shape)
            g = np.random.default_rng()
            np.random.seed(0)
            """)
        assert [v.rule for v in vs] == ["GL105"] * 3

    def test_negative_seeded_and_scoped_paths(self):
        assert rule_ids("""
            import numpy as np
            r = np.random.default_rng(1234)
            s = np.random.SeedSequence(7)
            def gen(seed):
                return np.random.default_rng(seed).normal()
            """) == []
        src = "import numpy as np\nx = np.random.rand(3)\n"
        assert rule_ids(src, path="bigdl_tpu/dataset/mnist.py") == []
        assert rule_ids(src, path="tests/test_foo.py") == []


# ===========================================================================
# GL106 recompile-hazard
# ===========================================================================
class TestRecompile:
    def test_positive_inline_jit_per_call(self):
        vs = lint("""
            import jax
            def train_step(params, x):
                return jax.jit(lambda p, v: p * v)(params, x)
            """)
        assert [v.rule for v in vs] == ["GL106"]
        assert "fresh jit cache" in vs[0].message

    def test_positive_jit_in_loop(self):
        vs = lint("""
            import jax
            def sweep(fns, x):
                outs = []
                for f in fns:
                    outs.append(jax.jit(f))
                return outs
            """)
        assert [v.rule for v in vs] == ["GL106"]
        assert "loop" in vs[0].message

    def test_positive_scalar_literal_without_static_decl(self):
        vs = lint("""
            import jax
            @jax.jit
            def step(params, use_bias):
                return params
            def run(p):
                return step(p, True)
            """)
        assert [v.rule for v in vs] == ["GL106"]
        assert "static_argnums" in vs[0].message

    def test_negative_static_argnames_on_assign_binding(self):
        # static_argnames on a `g = jax.jit(f, ...)` binding must
        # exonerate positional literals via f's param names
        assert rule_ids("""
            import jax
            def step(params, use_bias):
                return params
            fast = jax.jit(step, static_argnames=("use_bias",))
            def run(p):
                return fast(p, True)
            """) == []

    def test_negative_hoisted_and_declared_static(self):
        assert rule_ids("""
            import jax
            from functools import partial
            @partial(jax.jit, static_argnums=(1,))
            def step(params, use_bias):
                return params
            fast = jax.jit(step, static_argnums=(1,))
            def run(p, lr):
                return fast(p, True) + step(p, False) + step(p, lr)
            """) == []


# ===========================================================================
# GL107 driver-loop host sync
# ===========================================================================
OPTIM = "bigdl_tpu/optim/fake.py"


class TestDriverLoopHostSync:
    def test_positive_float_on_step_output_in_while_loop(self):
        vs = lint("""
            import jax
            from functools import partial
            def optimize(params, ostate, batches, done):
                @partial(jax.jit, donate_argnums=(0, 1))
                def train_step(params, ostate, x):
                    return params, ostate, (params * x).sum()
                while not done():
                    x = next(batches)
                    params, ostate, loss = train_step(params, ostate, x)
                    loss = float(loss)
                return params
            """, path=OPTIM)
        assert [v.rule for v in vs] == ["GL107"]
        assert "driver loop" in vs[0].message

    def test_positive_asarray_item_and_jit_assign_binding(self):
        vs = lint("""
            import jax
            import numpy as np
            def _step(p, x):
                return p, x.sum()
            def optimize(p, batches):
                step = jax.jit(_step, donate_argnums=(0,))
                for x in batches:
                    p, loss = step(p, x)
                    a = np.asarray(loss)
                    b = loss.item()
                return p
            """, path=OPTIM)
        assert [v.rule for v in vs] == ["GL107"] * 2

    def test_negative_deferred_one_step_behind_fetch(self):
        # the fix GL107 prescribes: sync the PREVIOUS iteration's value
        # before the dispatch rebinds it — sync-above-producer is clean
        assert rule_ids("""
            import jax
            from functools import partial
            def optimize(params, ostate, batches, done):
                @partial(jax.jit, donate_argnums=(0, 1))
                def train_step(params, ostate, x):
                    return params, ostate, (params * x).sum()
                prev = None
                while not done():
                    if prev is not None:
                        lv = float(prev)
                    params, ostate, prev = train_step(
                        params, ostate, next(batches))
                return params
            """, path=OPTIM) == []

    def test_negative_non_donating_jit_is_an_eval_loop(self):
        # predict/evaluate loops legitimately fetch each batch's output;
        # the donating signature is what marks a TRAINING step
        assert rule_ids("""
            import jax
            import numpy as np
            def evaluate(params, batches):
                fwd = jax.jit(lambda p, x: (p * x).sum())
                outs = []
                for x in batches:
                    out = fwd(params, x)
                    outs.append(np.asarray(out))
                return outs
            """, path=OPTIM) == []

    def test_negative_outside_optim_path(self):
        src = """
            import jax
            from functools import partial
            def drive(p, xs, done):
                @partial(jax.jit, donate_argnums=(0,))
                def step(p, x):
                    return p, x.sum()
                while not done():
                    p, loss = step(p, next(xs))
                    float(loss)
                return p
            """
        assert "GL107" not in rule_ids(src, path="bigdl_tpu/utils/fake.py")
        assert "GL107" not in rule_ids(src, path="tests/test_fake.py")


# ===========================================================================
# GL201 unguarded-shared-state
# ===========================================================================
SERV = "bigdl_tpu/serving/fake.py"


class TestUnguardedSharedState:
    def test_positive_annotated_attr_accessed_outside_lock(self):
        vs = lint("""
            import threading
            class B:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._q = []   # guarded-by: _cond
                    self._n = 0    # write-guarded-by: _cond
                def bad_read(self):
                    return len(self._q)
                def bad_write(self):
                    self._n = 5
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL201"] * 2
        assert "read of `self._q`" in vs[0].message
        assert "write to `self._n`" in vs[1].message

    def test_negative_locked_access_and_write_guarded_read(self):
        assert rule_ids("""
            import threading
            class B:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._q = []   # guarded-by: _cond
                    self._n = 0    # write-guarded-by: _cond
                def ok(self):
                    with self._cond:
                        self._q.append(1)
                        self._n += 1
                def ok_read(self):
                    return self._n  # write-guarded: reads lock-free
            """, path=SERV) == []

    def test_negative_held_on_entry_def_annotation(self):
        # the ModelRegistry._resolve contract: caller holds the lock,
        # the def-line annotation makes the body check as locked
        assert rule_ids("""
            import threading
            class R:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._services = {}  # guarded-by: _lock
                # guarded-by: _lock
                def _resolve(self, name):
                    return self._services[name]
                def get(self, name):
                    with self._lock:
                        return self._resolve(name)
            """, path=SERV) == []

    def test_negative_condition_aliasing_counts_as_the_lock(self):
        # Condition(self._lock) IS self._lock (the ReplicaSet._wake
        # shape): holding either guards attrs declared on the lock
        assert rule_ids("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._wake = threading.Condition(self._lock)
                    self._inflight = {}  # guarded-by: _lock
                def a(self):
                    with self._wake:
                        self._inflight.clear()
                def b(self):
                    with self._lock:
                        return len(self._inflight)
            """, path=SERV) == []

    def test_positive_heuristic_cross_thread_write_without_lock(self):
        vs = lint("""
            import threading
            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._t = None
                def start(self):
                    self.value = 0
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)
                    self._t.start()
                def _run(self):
                    self.value = 1
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL201"]
        assert "spawned thread" in vs[0].message

    def test_negative_heuristic_common_lock_on_both_writes(self):
        assert rule_ids("""
            import threading
            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._t = None
                def start(self):
                    with self._lock:
                        self.value = 0
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)
                    self._t.start()
                def _run(self):
                    with self._lock:
                        self.value = 1
            """, path=SERV) == []

    def test_positive_module_global_write_guard(self):
        vs = lint("""
            import threading
            _install_lock = threading.Lock()
            # write-guarded-by: _install_lock
            _installed = None
            def install(x):
                global _installed
                _installed = x
            def current():
                return _installed
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL201"]
        assert vs[0].message.startswith("write to `_installed`")

    def test_negative_local_shadow_of_guarded_global(self):
        # review regression: a function-local variable (or parameter)
        # that shadows an annotated module global is NOT the global —
        # Python scoping makes every occurrence local
        assert rule_ids("""
            import threading
            _install_lock = threading.Lock()
            # write-guarded-by: _install_lock
            _installed = None
            def probe():
                _installed = object()
                return _installed
            def probe2(_installed):
                _installed = None
                return _installed
            def real_write(x):
                global _installed
                with _install_lock:
                    _installed = x
            """, path=SERV) == []

    def test_negative_tests_are_out_of_scope(self):
        src = """
            import threading
            class B:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._q = []   # guarded-by: _cond
                def bad(self):
                    return len(self._q)
            """
        assert rule_ids(src, path="tests/test_fake.py") == []


# ===========================================================================
# GL202 lock-retake / lock-ordering
# ===========================================================================
class TestLockRetake:
    def test_positive_retake_via_method_call(self):
        # the ModelRegistry._resolve deadlock class: an error path under
        # the lock calls a helper that re-takes the same Lock
        vs = lint("""
            import threading
            class R:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._services = {}
                def get(self, name):
                    with self._lock:
                        if name not in self._services:
                            raise KeyError(self.list_models())
                        return self._services[name]
                def list_models(self):
                    with self._lock:
                        return sorted(self._services)
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL202"]
        assert "list_models" in vs[0].message
        assert "re-take" in vs[0].message

    def test_positive_direct_nested_with_same_lock(self):
        vs = lint("""
            import threading
            class R:
                def __init__(self):
                    self._lock = threading.Lock()
                def f(self):
                    with self._lock:
                        with self._lock:
                            pass
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL202"]

    def test_negative_rlock_and_default_condition_are_reentrant(self):
        assert rule_ids("""
            import threading
            class R:
                def __init__(self):
                    self._rlock = threading.RLock()
                    self._cond = threading.Condition()
                def f(self):
                    with self._rlock:
                        with self._rlock:
                            pass
                def g(self):
                    with self._cond:
                        self.h()
                def h(self):
                    with self._cond:
                        pass
            """, path=SERV) == []

    def test_positive_inconsistent_lock_order(self):
        vs = lint("""
            import threading
            class T:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def f(self):
                    with self._a:
                        with self._b:
                            pass
                def g(self):
                    with self._b:
                        with self._a:
                            pass
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL202"]
        assert "inconsistent lock order" in vs[0].message

    def test_negative_consistent_two_lock_order(self):
        assert rule_ids("""
            import threading
            class T:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def f(self):
                    with self._a:
                        with self._b:
                            pass
                def g(self):
                    with self._a:
                        with self._b:
                            pass
            """, path=SERV) == []

    def test_positive_held_on_entry_method_called_without_lock(self):
        vs = lint("""
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                # guarded-by: _lock
                def _mutate_locked(self):
                    self._n += 1
                def bad(self):
                    self._mutate_locked()
                def good(self):
                    with self._lock:
                        self._mutate_locked()
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL202"]
        assert "held on entry" in vs[0].message


# ===========================================================================
# GL203 future-settlement
# ===========================================================================
class TestFutureSettlement:
    def test_positive_popped_request_never_settled(self):
        # the settle-every-path class: a backlog sweep that pops
        # requests but resolves nothing strands every waiter
        vs = lint("""
            import threading
            from collections import deque
            class B:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._q = deque()
                def _cancel_backlog(self):
                    rows = 0
                    while True:
                        with self._cond:
                            if not self._q:
                                return rows
                            req = self._q.popleft()
                        rows += req.n_rows
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL203"]
        assert "never settled" in vs[0].message

    def test_negative_cancel_counts_as_settlement(self):
        assert rule_ids("""
            import threading
            from collections import deque
            class B:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._q = deque()
                def _cancel_backlog(self):
                    rows = 0
                    while True:
                        with self._cond:
                            if not self._q:
                                return rows
                            req = self._q.popleft()
                        if req.future.cancel():
                            rows += req.n_rows
            """, path=SERV) == []

    def test_positive_bare_pop_statement_discards(self):
        vs = lint("""
            class B:
                def drain(self, out_q):
                    out_q.get_nowait()
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL203"]
        assert "discarded" in vs[0].message

    def test_negative_handoff_and_settle_paths(self):
        # append to a batch (hand-off), settle_future(...), unpack then
        # invoke (the AsyncSnapshotWriter shape), subexpression pops
        assert rule_ids("""
            from collections import deque
            def collect(q, dispatch_fn):
                batch = []
                first = q.popleft()
                batch.append(first)
                dispatch_fn(batch)
            def on_done(inflight, token):
                entry = inflight.pop(token, None)
                route, inner = entry
                settle_future(inner, result=1)
            def writer_loop(job_q):
                item = job_q.get()
                job, context = item
                job()
            def drain_results(inflight):
                return [inflight.pop(0).result() for _ in range(3)]
            """, path=SERV) == []

    def test_negative_dict_get_lookup_is_not_a_pop(self):
        assert rule_ids("""
            def route(inflight, token):
                entry = inflight.get(token)
                return entry
            """, path=SERV) == []


# ===========================================================================
# GL204 thread-lifecycle
# ===========================================================================
class TestThreadLifecycle:
    def test_positive_nondaemon_never_joined(self):
        vs = lint("""
            import threading
            def spawn(fn):
                t = threading.Thread(target=fn)
                t.start()
                return t
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL204"]
        assert "neither daemon" in vs[0].message

    def test_positive_unbound_thread_discarded(self):
        vs = lint("""
            import threading
            def fire_and_forget(fn):
                threading.Thread(target=fn, daemon=True).start()
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL204"]
        assert "never bound" in vs[0].message

    def test_negative_daemon_bound_and_joined_variants(self):
        assert rule_ids("""
            import threading
            class S:
                def start(self):
                    self._thread = threading.Thread(target=self._run,
                                                    daemon=True)
                    self._thread.start()
                def stop(self):
                    self._thread.join(timeout=2.0)
                def _run(self):
                    pass
            def run_once(fn):
                t = threading.Thread(target=fn)
                t.start()
                t.join()
            """, path=SERV) == []

    def test_positive_join_in_another_class_does_not_exonerate(self):
        # review regression: the joined/daemon search is scoped to the
        # binding's own class — a same-named `self._thread` joined in
        # a DIFFERENT class must not mask this class's orphan
        vs = lint("""
            import threading
            class Joins:
                def start(self):
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()
                def stop(self):
                    self._thread.join()
                def _run(self):
                    pass
            class Orphans:
                def start(self):
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()
                def _run(self):
                    pass
            """, path=SERV)
        # exactly one finding, anchored inside the non-joining class
        assert [v.rule for v in vs] == ["GL204"]
        assert vs[0].line > 10

    def test_negative_listcomp_bound_threads_joined_via_loop(self):
        # the bench/autotune shape: a pool of workers joined through
        # iteration over the container binding
        assert rule_ids("""
            import threading
            def sweep(fns):
                workers = [threading.Thread(target=f) for f in fns]
                for t in workers:
                    t.start()
                for t in workers:
                    t.join()
            """, path=SERV) == []


# ===========================================================================
# GL205 wait-predicate
# ===========================================================================
class TestWaitPredicate:
    def test_positive_wait_under_if(self):
        vs = lint("""
            import threading
            class P:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.ready = False
                def bad(self):
                    with self._cond:
                        if not self.ready:
                            self._cond.wait()
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL205"]
        assert "while" in vs[0].message

    def test_negative_wait_in_while_loop(self):
        assert rule_ids("""
            import threading
            class P:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.ready = False
                def good(self):
                    with self._cond:
                        while not self.ready:
                            self._cond.wait()
                def supervise(self):
                    while True:
                        with self._cond:
                            self._cond.wait(timeout=1.0)
            """, path=SERV) == []

    def test_negative_event_wait_is_not_a_condition(self):
        assert rule_ids("""
            import threading
            def waiter(stop_event):
                stop_event.wait(0.5)
            """, path=SERV) == []


# ===========================================================================
# GL206 blocking-under-lock
# ===========================================================================
class TestBlockingUnderLock:
    def test_positive_sleep_result_fsync_under_lock(self):
        vs = lint("""
            import os
            import threading
            import time
            class D:
                def __init__(self):
                    self._lock = threading.Lock()
                def bad(self, fut, fd):
                    with self._lock:
                        time.sleep(0.1)
                        out = fut.result()
                        os.fsync(fd)
                    return out
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL206"] * 3

    def test_positive_wait_on_foreign_condition_under_lock(self):
        vs = lint("""
            import threading
            class D:
                def __init__(self):
                    self._a = threading.Lock()
                    self._c = threading.Condition()
                def cross(self):
                    with self._a:
                        with self._c:
                            pass
                def bad(self):
                    with self._a:
                        while True:
                            self._c.wait()
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL206"]
        assert "waiting on `self._c`" in vs[0].message

    def test_negative_wait_on_held_condition_releases_it(self):
        assert rule_ids("""
            import threading
            class D:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.ready = False
                def ok(self):
                    with self._cond:
                        while not self.ready:
                            self._cond.wait()
            """, path=SERV) == []

    def test_negative_blocking_outside_lock_and_re_compile(self):
        assert rule_ids("""
            import re
            import threading
            import time
            class D:
                def __init__(self):
                    self._lock = threading.Lock()
                def ok(self, fut):
                    with self._lock:
                        pat = re.compile("x+")
                    time.sleep(0.1)
                    return fut.result(), pat
            """, path=SERV) == []

    def test_positive_xla_compile_under_lock(self):
        vs = lint("""
            import threading
            class S:
                def __init__(self, jit):
                    self._warm_lock = threading.Lock()
                    self._jit = jit
                    self._compiled = {}
                def warmup(self, params, spec):
                    with self._warm_lock:
                        self._compiled[1] = self._jit.lower(
                            params, spec).compile()
            """, path=SERV)
        assert [v.rule for v in vs] == ["GL206"]
        assert "XLA compile" in vs[0].message


# ===========================================================================
# GL2xx suppressions + reverted-hazard regression fixtures
# ===========================================================================
class TestGL2Suppressions:
    def test_trailing_suppression_scopes_to_line(self):
        src = ("import threading\n"
               "class B:\n"
               "    def __init__(self):\n"
               "        self._cond = threading.Condition()\n"
               "        self._q = []   # guarded-by: _cond\n"
               "    def racy_hint(self):\n"
               "        return len(self._q)  # graftlint: disable=GL201\n"
               "    def still_bad(self):\n"
               "        return len(self._q)\n")
        vs = lint_source(src, path=SERV)
        assert [(v.rule, v.line) for v in vs] == [("GL201", 9)]

    def test_rule_name_alias_suppresses(self):
        src = ("import threading\n"
               "def fire(fn):\n"
               "    # supervised externally"
               "  graftlint: disable=thread-lifecycle\n"
               "    threading.Thread(target=fn, daemon=True).start()\n")
        assert lint_source(src, path=SERV) == []


class TestRevertedHazards:
    """The acceptance gate: real concurrency-bug classes from the PR
    5/10/11 review rounds, re-created by reverting their fixes in
    fixture form, must be caught by the family."""

    def test_resolve_lock_retake_revert_is_caught(self):
        # PR 5 review: ModelRegistry._resolve's KeyError path re-took
        # the non-reentrant registry lock through a helper — deadlock.
        # The fix documented the caller-must-hold contract; reverting
        # it (helper re-acquires) must fire GL202.
        src = """
            import threading
            class ModelRegistry:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._services = {}
                    self._latest = {}
                # guarded-by: _lock
                def _resolve(self, name, version):
                    if name not in self._latest:
                        raise KeyError(
                            f"no model; have {self.list_models()}")
                    return (name, self._latest[name])
                def list_models(self):
                    with self._lock:
                        return sorted(self._services)
                def get(self, name, version=None):
                    with self._lock:
                        return self._services[
                            self._resolve(name, version)]
            """
        vs = lint(src, path="bigdl_tpu/serving/registry_reverted.py")
        assert [v.rule for v in vs] == ["GL202"]
        assert "deadlock" in vs[0].message

    def test_settle_every_path_revert_is_caught(self):
        # PR 5/10 invariant "accepted requests ALWAYS resolve": the
        # batcher's cancel path settles every popped future.  Reverting
        # the settle (pop-and-count only) must fire GL203.
        src = """
            import threading
            from collections import deque
            class RequestBatcher:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._q = deque()
                    self.cancelled_rows = 0
                def _cancel_backlog(self):
                    rows = 0
                    while True:
                        with self._cond:
                            if not self._q:
                                self.cancelled_rows += rows
                                return rows
                            req = self._q.popleft()
                        rows += req.n_rows
            """
        vs = lint(src, path="bigdl_tpu/serving/batcher_reverted.py")
        assert [v.rule for v in vs] == ["GL203"]

    def test_fixed_shapes_stay_silent(self):
        # the shipped fixes of both classes lint clean — the rules
        # gate the regression, not the idiom
        assert rule_ids("""
            import threading
            from collections import deque
            class RequestBatcher:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._q = deque()
                    self.cancelled_rows = 0
                def _cancel_backlog(self):
                    rows = 0
                    while True:
                        with self._cond:
                            if not self._q:
                                self.cancelled_rows += rows
                                return rows
                            req = self._q.popleft()
                        if req.future.cancel():
                            rows += req.n_rows
            """, path="bigdl_tpu/serving/batcher_fixed.py") == []

    # -- ISSUE 15: the PR-14 review-round-4 classes, reverted on the
    # -- REAL source (string surgery, then lint) — the strongest gate:
    # -- annotation drift that would blind the rule fails here too
    def test_pin_leak_revert_on_real_server_is_caught(self):
        src = open(os.path.join(REPO, "bigdl_tpu", "frontend",
                                "server.py")).read()
        guarded = ("                try:  # pin held: EVERY exit path "
                   "below must unpin\n"
                   "                    max_batch = "
                   "self._backend_max_batch(backend)")
        reverted = ("                max_batch = "
                    "self._backend_max_batch(backend)\n"
                    "                try:  # pin held: EVERY exit path "
                    "below must unpin")
        assert guarded in src, "server.py pin/try shape moved — " \
            "update this surgery (and keep the pin inside the try)"
        vs = lint_source(src.replace(guarded, reverted),
                         path="bigdl_tpu/frontend/server.py")
        assert "GL301" in {v.rule for v in vs}
        (v,) = [v for v in vs if v.rule == "GL301"]
        assert "wire_inflight" in v.message

    def test_blanket_400_revert_on_real_classify_is_caught(self):
        src = open(os.path.join(REPO, "bigdl_tpu", "frontend",
                                "server.py")).read()
        tail = '        return 500, {"error": f"{type(e).__name__}: ' \
               '{e}"}, {}'
        assert tail in src, "server.py _classify tail moved — " \
            "update this surgery"
        reverted = ('        if isinstance(e, (ValueError, TypeError)):\n'
                    '            return 400, {"error": str(e)}, {}\n'
                    + tail)
        vs = lint_source(src.replace(tail, reverted),
                         path="bigdl_tpu/frontend/server.py")
        assert "GL302" in {v.rule for v in vs}
        (v,) = [v for v in vs if v.rule == "GL302"]
        assert "ValueError" in v.message


# ===========================================================================
# GL301 leaked-acquire
# ===========================================================================
_PIN_PRELUDE = """
    import threading
    class _WireInflight:
        def __init__(self):
            self._cond = threading.Condition()
            self._counts = {}
        def enter(self, key):  # acquires: wire_inflight
            with self._cond:
                self._counts[key] = self._counts.get(key, 0) + 1  # acquires: wire_inflight
        def exit(self, key):  # releases: wire_inflight
            with self._cond:
                self._counts.pop(key, None)  # releases: wire_inflight
"""


class TestLeakedAcquire:
    def test_positive_statement_between_acquire_and_try(self):
        # the PR-14 shape: one fallible statement between the pin and
        # its try/finally leaks the pin on a raise
        vs = lint(_PIN_PRELUDE + """
    class Server:
        # acquires: wire_inflight
        def _resolve_pinned(self, name, version):
            key = (name, version)
            self.inflight.enter(key)
            return key, self.backend
        def _run_predict(self, name, version, x):
            key, backend = self._resolve_pinned(name, version)
            max_batch = int(backend.max_batch_size)
            try:
                return self._predict(backend, x, max_batch)
            finally:
                self.inflight.exit(key)
            """, path="bigdl_tpu/frontend/server_fx.py")
        assert [v.rule for v in vs] == ["GL301"]
        assert "wire_inflight" in vs[0].message

    def test_negative_next_statement_try_finally_release(self):
        assert rule_ids(_PIN_PRELUDE + """
    class Server:
        # acquires: wire_inflight
        def _resolve_pinned(self, name, version):
            key = (name, version)
            self.inflight.enter(key)
            return key, self.backend
        def _run_predict(self, name, version, x):
            key, backend = self._resolve_pinned(name, version)
            try:
                max_batch = int(backend.max_batch_size)
                return self._predict(backend, x, max_batch)
            finally:
                self.inflight.exit(key)
            """, path="bigdl_tpu/frontend/server_fx.py") == []

    def test_negative_acquire_inside_protected_try(self):
        # acquiring INSIDE a try whose finally releases is also safe
        # (the release tolerates a never-completed acquire)
        assert rule_ids(_PIN_PRELUDE + """
    class Server:
        # acquires: wire_inflight
        def _resolve_pinned(self, name, version):
            key = (name, version)
            self.inflight.enter(key)
            return key, self.backend
        def _run_predict(self, name, version, x):
            key = (name, version)
            backend = None
            try:
                key, backend = self._resolve_pinned(name, version)
                return self._predict(backend, x)
            finally:
                self.inflight.exit(key)
            """, path="bigdl_tpu/frontend/server_fx.py") == []

    def test_negative_ownership_transfer_def_annotation(self):
        # a caller that is ITSELF `# acquires:`-annotated passes the
        # obligation up — its own body is exempt for that resource
        assert rule_ids(_PIN_PRELUDE + """
    class Server:
        # acquires: wire_inflight
        def _resolve_pinned(self, name, version):
            key = (name, version)
            self.inflight.enter(key)
            if self.registry is None:
                raise KeyError(name)
            return key, self.backend
            """, path="bigdl_tpu/frontend/server_fx.py") == []

    def test_positive_unprotected_call_in_loop_body(self):
        vs = lint(_PIN_PRELUDE + """
    class Server:
        # acquires: wire_inflight
        def _resolve_pinned(self, name, version):
            self.inflight.enter((name, version))
            return (name, version)
        def drain_all(self, names):
            for n in names:
                key = self._resolve_pinned(n, None)
                self.log(key)
            """, path="bigdl_tpu/frontend/server_fx.py")
        assert [v.rule for v in vs] == ["GL301"]

    def test_negative_tests_are_out_of_scope(self):
        assert rule_ids(_PIN_PRELUDE + """
    class Server:
        # acquires: wire_inflight
        def _resolve_pinned(self, name, version):
            self.inflight.enter((name, version))
            return (name, version)
        def use(self):
            k = self._resolve_pinned("m", 1)
            self.log(k)
            """, path="tests/test_server_fx.py") == []

    def test_positive_acquire_inside_match_case_body(self):
        # review regression: match/case bodies are blocks too — an
        # unprotected acquire inside one must not pass silently
        vs = lint(_PIN_PRELUDE + """
    class Server:
        # acquires: wire_inflight
        def _resolve_pinned(self, name, version):
            self.inflight.enter((name, version))
            return (name, version)
        def route(self, kind, name):
            match kind:
                case "predict":
                    key = self._resolve_pinned(name, None)
                    self.log(key)
                case _:
                    pass
            """, path="bigdl_tpu/frontend/server_fx.py")
        assert [v.rule for v in vs] == ["GL301"]


# ===========================================================================
# GL302 error-taxonomy
# ===========================================================================
class TestErrorTaxonomy:
    def test_positive_blanket_except_feeding_400(self):
        vs = lint("""
            class Handler:
                def parse(self, body):
                    try:
                        return self.decode(body)
                    except Exception as e:
                        raise _HTTPError(400, f"bad body: {e}")
            """, path="bigdl_tpu/frontend/server_fx.py")
        assert [v.rule for v in vs] == ["GL302"]
        assert "blanket" in vs[0].message

    def test_positive_isinstance_classifier_on_undeclared_type(self):
        # THE PR-14 bug: blanket ValueError/TypeError -> 400 in the
        # status classifier hides internal bugs from the 5xx SLO
        vs = lint("""
            class Server:
                @staticmethod
                def _classify(e):
                    if isinstance(e, (ValueError, TypeError)):
                        return 400, {"error": str(e)}, {}
                    return 500, {"error": str(e)}, {}
            """, path="bigdl_tpu/frontend/server_fx.py")
        assert [v.rule for v in vs] == ["GL302"]
        assert "ValueError" in vs[0].message

    def test_negative_declared_types_may_map_4xx(self):
        assert rule_ids("""
            class Server:
                @staticmethod
                def _classify(e):
                    if isinstance(e, _HTTPError):
                        return e.status, e.body, e.headers
                    if isinstance(e, UnknownTenantError):
                        return 403, {"error": str(e)}, {}
                    if isinstance(e, RequestSpecError):
                        return 400, {"error": str(e)}, {}
                    return 500, {"error": str(e)}, {}
            """, path="bigdl_tpu/frontend/server_fx.py") == []

    def test_negative_narrow_typed_wrap_at_origin_is_blessed(self):
        # individually-wrapped client-input parse sites (the round-4
        # fix pattern) stay silent: the caught type is SPECIFIC to the
        # guarded operation
        assert rule_ids("""
            class Handler:
                def parse_len(self, headers):
                    try:
                        return int(headers.get("Content-Length", -1))
                    except ValueError:
                        raise _HTTPError(400, "bad Content-Length")
            """, path="bigdl_tpu/frontend/server_fx.py") == []

    def test_negative_5xx_from_blanket_except_is_fine(self):
        # mapping unknown errors to 500 is the CORRECT taxonomy
        assert rule_ids("""
            class Handler:
                def run(self, body):
                    try:
                        return self.dispatch(body)
                    except Exception as e:
                        self.send_json(500, {"error": str(e)})
            """, path="bigdl_tpu/frontend/server_fx.py") == []

    def test_negative_outside_wire_plane(self):
        # GL302 is scoped to frontend/ + serving/: HTTP statuses mean
        # nothing elsewhere
        assert rule_ids("""
            class Thing:
                def classify(self, e):
                    if isinstance(e, ValueError):
                        return 400
                    return 500
            """, path="bigdl_tpu/optim/thing_fx.py") == []

    def test_file_client_error_declaration_extends_taxonomy(self):
        assert rule_ids("""
            # graftlint: client-error=MyParseError
            class Server:
                @staticmethod
                def _classify(e):
                    if isinstance(e, MyParseError):
                        return 400, {"error": str(e)}, {}
                    return 500, {"error": str(e)}, {}
            """, path="bigdl_tpu/frontend/server_fx.py") == []

    def test_positive_bare_except_sending_4xx(self):
        vs = lint("""
            class Handler:
                def go(self, req):
                    try:
                        self.handle(req)
                    except:
                        self.send_json(404, {"error": "nope"})
            """, path="bigdl_tpu/serving/handler_fx.py")
        assert [v.rule for v in vs] == ["GL302"]


# ===========================================================================
# GL303 release-on-all-paths
# ===========================================================================
class TestReleaseOnAllPaths:
    def test_positive_one_way_counter(self):
        vs = lint("""
            import threading
            class Health:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._probe_inflight = False
                def admit(self):
                    with self._lock:
                        self._probe_inflight = True  # acquires: probe_slot
                        return "probe"
            """, path="bigdl_tpu/resilience/health_fx.py")
        assert [v.rule for v in vs] == ["GL303"]
        assert "probe_slot" in vs[0].message

    def test_positive_unannotated_mutation_of_tracked_counter(self):
        # a new inc/dec added outside the discipline — the PR-10
        # probe-slot leak entered exactly this way
        vs = lint("""
            import threading
            class Health:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._probe_inflight = False
                def admit(self):
                    with self._lock:
                        self._probe_inflight = True  # acquires: probe_slot
                        return "probe"
                def cancel_probe(self):
                    with self._lock:
                        self._probe_inflight = False  # releases: probe_slot
                def sneaky_reset(self):
                    self._probe_inflight = False
            """, path="bigdl_tpu/resilience/health_fx.py")
        assert [v.rule for v in vs] == ["GL303"]
        assert "sneaky" not in vs[0].message  # message names the attr
        assert "_probe_inflight" in vs[0].message

    def test_negative_paired_and_fully_annotated(self):
        assert rule_ids("""
            import threading
            class Batcher:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._q_rows = 0
                def put(self, req):
                    with self._cond:
                        self._q_rows += req.n_rows  # acquires: queue_rows
                def pop(self, req):
                    with self._cond:
                        self._q_rows -= req.n_rows  # releases: queue_rows
            """, path="bigdl_tpu/serving/batcher_fx.py") == []

    def test_negative_init_mutation_exempt(self):
        # construction happens-before sharing: the __init__ zero needs
        # no annotation (same exemption as GL201)
        assert rule_ids("""
            import threading
            class Counts:
                def __init__(self):
                    self._n = 0
                def inc(self):
                    self._n += 1  # acquires: slots
                def dec(self):
                    self._n -= 1  # releases: slots
            """, path="bigdl_tpu/serving/counts_fx.py") == []

    def test_negative_unannotated_files_are_silent(self):
        # the rule is annotation-driven: no annotations, no opinions
        assert rule_ids("""
            class Plain:
                def bump(self):
                    self._n += 1
            """, path="bigdl_tpu/serving/plain_fx.py") == []


# ===========================================================================
# GL401 divergent-collective
# ===========================================================================
class TestDivergentCollective:
    def test_positive_process_index_branch(self):
        vs = lint("""
            import jax
            from jax.experimental import multihost_utils
            def maybe_sync(tag):
                if jax.process_index() == 0:
                    multihost_utils.sync_global_devices(tag)
            """, path="bigdl_tpu/parallel/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL401"]
        assert "one-sided" in vs[0].message

    def test_positive_tainted_name_predicate(self):
        # taint flows through the assignment: rank IS process-local
        vs = lint("""
            import jax
            from jax.experimental import multihost_utils
            def gather(arr):
                rank = jax.process_index()
                if rank == 0:
                    return multihost_utils.process_allgather(arr)
            """, path="bigdl_tpu/parallel/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL401"]

    def test_positive_filesystem_predicate(self):
        # the filesystem is per-host: an exists() gate diverges
        vs = lint("""
            import os
            from jax.experimental import multihost_utils
            def gather(arr, path):
                if os.path.exists(path):
                    return multihost_utils.process_allgather(arr)
            """, path="bigdl_tpu/parallel/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL401"]

    def test_positive_collective_reached_through_helper(self):
        # same-file closure: the branch calls a helper that collects
        vs = lint("""
            import time
            from jax.experimental import multihost_utils
            def _sync(arr):
                return multihost_utils.process_allgather(arr)
            def gather(arr, deadline):
                if time.monotonic() > deadline:
                    return _sync(arr)
            """, path="bigdl_tpu/parallel/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL401"]

    def test_positive_ifexp_arm(self):
        vs = lint("""
            import time
            from jax.experimental import multihost_utils
            def gather(arr, t0):
                return (multihost_utils.process_allgather(arr)
                        if time.monotonic() > t0 else arr)
            """, path="bigdl_tpu/parallel/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL401"]
        assert "both arms" in vs[0].message

    def test_negative_uniform_predicate(self):
        # process_count is the same value on every process
        assert rule_ids("""
            import jax
            from jax.experimental import multihost_utils
            def gather(arr):
                if jax.process_count() > 1:
                    return multihost_utils.process_allgather(arr)
                return arr
            """, path="bigdl_tpu/parallel/fake_spmd.py") == []

    def test_negative_replicated_by_on_branch(self):
        assert rule_ids("""
            import os
            from jax.experimental import multihost_utils
            def gather(arr, path):
                # the flag file is written by the membership ledger on
                # every host at the same epoch
                # replicated-by: membership-epoch-ledger
                if os.path.exists(path):
                    return multihost_utils.process_allgather(arr)
            """, path="bigdl_tpu/parallel/fake_spmd.py") == []

    def test_negative_replicated_by_on_predicate_assignment(self):
        # annotating the assignment that PRODUCES the predicate clears
        # the taint at its source
        assert rule_ids("""
            import os
            from jax.experimental import multihost_utils
            def gather(arr, path):
                armed = os.path.exists(path)  # replicated-by: config-derived
                if armed:
                    return multihost_utils.process_allgather(arr)
            """, path="bigdl_tpu/parallel/fake_spmd.py") == []

    def test_negative_tests_and_datasets_exempt(self):
        src = """
            import jax
            from jax.experimental import multihost_utils
            def maybe_sync(tag):
                if jax.process_index() == 0:
                    multihost_utils.sync_global_devices(tag)
            """
        assert rule_ids(src, path="tests/fake_spmd.py") == []
        assert rule_ids(src, path="bigdl_tpu/dataset/fake_spmd.py") == []


# ===========================================================================
# GL402 world-size-dependent-state
# ===========================================================================
class TestWorldSizeDependentState:
    def test_positive_schema_without_bucket_content(self):
        vs = lint("""
            def checkpoint_schema(plan):
                return build_schema(n_shard=8,
                                    bucket_sizes=plan.sizes)
            """, path="bigdl_tpu/parallel/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL402"]
        assert "bucket_content" in vs[0].message

    def test_positive_world_size_into_persisted_state(self):
        vs = lint("""
            import jax
            def snapshot(state):
                state["world"] = jax.process_count()
            """, path="bigdl_tpu/parallel/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL402"]
        assert "reshard_state" in vs[0].message

    def test_negative_schema_with_bucket_content(self):
        assert rule_ids("""
            def checkpoint_schema(plan):
                return build_schema(n_shard=8,
                                    bucket_sizes=plan.sizes,
                                    bucket_content=plan.content)
            """, path="bigdl_tpu/parallel/fake_spmd.py") == []

    def test_negative_reshard_path_exempts_the_function(self):
        assert rule_ids("""
            import jax
            def adopt(state, leaves, plan):
                state["world"] = jax.process_count()
                return reshard_state(leaves, plan)
            """, path="bigdl_tpu/parallel/fake_spmd.py") == []


# ===========================================================================
# GL403 replay-boundary-violation
# ===========================================================================
class TestReplayBoundaryViolation:
    def test_positive_fetch_outside_boundary(self):
        vs = lint("""
            import jax
            def peek_loss(losses):
                return jax.device_get(losses)
            """, path="bigdl_tpu/optim/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL403"]
        assert "replay boundary" in vs[0].message

    def test_positive_restore_outside_boundary(self):
        vs = lint("""
            def hot_reload(mgr, target, ckpt):
                return mgr.restore_into(target, ckpt)
            """, path="bigdl_tpu/resilience/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL403"]

    def test_negative_annotated_boundary_def(self):
        assert rule_ids("""
            import jax
            # replay-boundary: callers reach this only at block edges
            def capture(losses):
                return jax.device_get(losses)
            """, path="bigdl_tpu/optim/fake_spmd.py") == []

    def test_negative_nested_def_inherits_boundary(self):
        # the ancestor chain carries the boundary: a closure inside a
        # boundary def needs no annotation of its own
        assert rule_ids("""
            import jax
            # replay-boundary: block edge
            def replay(losses):
                def fetch():
                    return jax.device_get(losses)
                return fetch()
            """, path="bigdl_tpu/optim/fake_spmd.py") == []

    def test_negative_outside_replay_planes(self):
        # serving fetches freely: the rule's blast radius is the
        # optim/checkpoint/resilience planes
        assert rule_ids("""
            import jax
            def predict(out):
                return jax.device_get(out)
            """, path="bigdl_tpu/serving/fake_spmd.py") == []


# ===========================================================================
# GL404 collective-in-divergent-loop
# ===========================================================================
class TestCollectiveInDivergentLoop:
    def test_positive_unguarded_share_feeds_fast_forward(self):
        vs = lint("""
            def resume(records, scale, it):
                skip = records // scale
                return fast_forward_records(it, skip)
            """, path="bigdl_tpu/parallel/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL404"]
        assert "divisibility" in vs[0].message

    def test_positive_floored_trip_count_over_collective(self):
        vs = lint("""
            import jax
            def drain(total, hosts, xs):
                steps = total // hosts
                for _ in range(steps):
                    xs = jax.lax.psum(xs, "data")
                return xs
            """, path="bigdl_tpu/parallel/fake_spmd.py")
        assert [v.rule for v in vs] == ["GL404"]
        assert "trip count" in vs[0].message

    def test_negative_guarded_by_raise(self):
        assert rule_ids("""
            def resume(records, scale, it):
                if records % scale:
                    raise ValueError("indivisible mid-epoch counter")
                skip = records // scale
                return fast_forward_records(it, skip)
            """, path="bigdl_tpu/parallel/fake_spmd.py") == []

    def test_negative_guarded_by_assert(self):
        assert rule_ids("""
            import jax
            def drain(total, hosts, xs):
                assert total % hosts == 0
                steps = total // hosts
                for _ in range(steps):
                    xs = jax.lax.psum(xs, "data")
                return xs
            """, path="bigdl_tpu/parallel/fake_spmd.py") == []

    def test_negative_loop_without_collectives(self):
        assert rule_ids("""
            def chunk(total, hosts, xs):
                n = total // hosts
                out = []
                for i in range(n):
                    out.append(xs[i])
                return out
            """, path="bigdl_tpu/parallel/fake_spmd.py") == []


# ===========================================================================
# the `# replicated-by:` mechanism ledger (cross-file contract)
# ===========================================================================
class TestMechanismLedger:
    def _model(self, src, path):
        import ast as _ast
        from tools.graftlint import spmd
        src = textwrap.dedent(src)
        return spmd.SpmdModel(_ast.parse(src), src, path)

    def test_mirror_use_without_provider_is_reported(self):
        from tools.graftlint import spmd
        user = self._model("""
            from jax.experimental import multihost_utils
            def dedup(mgr, step, arr):
                # replicated-by: step-mirror
                if mgr.last_saved_step != step:
                    multihost_utils.sync_global_devices("save")
            """, "bigdl_tpu/optim/user.py")
        got = spmd.mechanism_ledger([user])
        assert [(p, m) for p, _ln, m in got] == [
            ("bigdl_tpu/optim/user.py", "step-mirror")]

    def test_provider_in_another_file_satisfies_the_use(self):
        from tools.graftlint import spmd
        user = self._model("""
            from jax.experimental import multihost_utils
            def dedup(mgr, step, arr):
                # replicated-by: step-mirror
                if mgr.last_saved_step != step:
                    multihost_utils.sync_global_devices("save")
            """, "bigdl_tpu/optim/user.py")
        provider = self._model("""
            def save(mgr, step):
                mgr.last_saved_step = step  # replicates: step-mirror
            """, "bigdl_tpu/checkpoint/provider.py")
        assert spmd.mechanism_ledger([user, provider]) == []

    def test_non_mirror_mechanisms_need_no_provider(self):
        from tools.graftlint import spmd
        user = self._model("""
            from jax.experimental import multihost_utils
            def gather(cfg, arr):
                # replicated-by: config-derived
                if cfg.multi_host:
                    multihost_utils.process_allgather(arr)
            """, "bigdl_tpu/optim/user.py")
        assert spmd.mechanism_ledger([user]) == []

    def test_real_tree_ledger_is_satisfied(self):
        # the shipped sources carry exactly the providers their
        # `*-mirror` uses demand
        import ast as _ast
        from tools.graftlint import spmd
        models = []
        for rel in ("bigdl_tpu/optim/optimizer.py",
                    "bigdl_tpu/optim/distri_optimizer.py"):
            src = open(os.path.join(REPO, rel)).read()
            models.append(spmd.SpmdModel(_ast.parse(src), src, rel))
        assert spmd.mechanism_ledger(models) == []

    def test_deleting_the_real_mirror_write_fails_the_ledger(self):
        # cross-file gate: the provider lives in distri_optimizer.py,
        # the uses in optimizer.py — deleting the provider annotation
        # (as a refactor dropping the mirror write would) must surface
        # at the USE sites
        import ast as _ast
        from tools.graftlint import spmd
        osrc = open(os.path.join(REPO, "bigdl_tpu", "optim",
                                 "optimizer.py")).read()
        dsrc = open(os.path.join(REPO, "bigdl_tpu", "optim",
                                 "distri_optimizer.py")).read()
        assert "# replicates: checkpoint-step-mirror" in dsrc, \
            "mirror-write provider annotation moved — update this test"
        dsrc = dsrc.replace("# replicates: checkpoint-step-mirror", "#")
        models = [
            spmd.SpmdModel(_ast.parse(osrc), osrc,
                           "bigdl_tpu/optim/optimizer.py"),
            spmd.SpmdModel(_ast.parse(dsrc), dsrc,
                           "bigdl_tpu/optim/distri_optimizer.py")]
        got = spmd.mechanism_ledger(models)
        assert {m for _p, _ln, m in got} == {"checkpoint-step-mirror"}
        assert all(p == "bigdl_tpu/optim/optimizer.py"
                   for p, _ln, _m in got)


# ===========================================================================
# the annotation conventions bind on the REAL sources
# ===========================================================================
class TestSpmdAnnotationsOnRealTree:
    FILES = ("bigdl_tpu/optim/optimizer.py",
             "bigdl_tpu/optim/distri_optimizer.py",
             "bigdl_tpu/optim/trigger.py",
             "bigdl_tpu/parallel/grad_sync.py",
             "bigdl_tpu/checkpoint/manager.py",
             "bigdl_tpu/resilience/membership.py")

    def _models(self):
        import ast as _ast
        from tools.graftlint import spmd
        out = {}
        for rel in self.FILES:
            src = open(os.path.join(REPO, rel)).read()
            out[rel] = spmd.SpmdModel(_ast.parse(src), src, rel)
        return out

    def test_replicated_by_census(self):
        # the seeded convention: >= 25 bound `# replicated-by:` lines
        # across the training/checkpoint/membership planes
        models = self._models()
        total = sum(len(m.replicated_lines) for m in models.values())
        assert total >= 25, f"only {total} replicated-by bindings bound"

    def test_replay_boundaries_bound_to_the_expected_defs(self):
        models = self._models()
        per_file = {rel: len(m.boundary_defs)
                    for rel, m in models.items()}
        assert per_file["bigdl_tpu/optim/optimizer.py"] >= 2
        assert per_file["bigdl_tpu/optim/distri_optimizer.py"] >= 3
        assert per_file["bigdl_tpu/checkpoint/manager.py"] >= 1

    def test_docstring_mentions_never_bind(self):
        # annotations live in COMMENT tokens only: a docstring QUOTING
        # the convention (rules/spmd.py does) must not create bindings
        import ast as _ast
        from tools.graftlint import spmd
        src = ('"""Doc quoting `# replicated-by: x-mirror` '
               'in prose."""\n'
               "x = 1\n")
        m = spmd.SpmdModel(_ast.parse(src), src, "bigdl_tpu/nn/d.py")
        assert m.replicated_lines == {}
        assert spmd.mechanism_ledger([m]) == []


# ===========================================================================
# ISSUE-17 acceptance: the two historical bugs, reverted on REAL source
# ===========================================================================
class TestRevertedSpmdHazards:
    def test_last_saved_step_mirror_revert_is_caught(self):
        # the PR-7 bug: without the every-process mirror write, the
        # `last_saved_step` dedup predicate is process-0-only and the
        # checkpoint collectives under it go one-sided.  Reverting the
        # annotation (as deleting the mirror would force) fires GL401.
        src = open(os.path.join(REPO, "bigdl_tpu", "optim",
                                "optimizer.py")).read()
        needle = "# replicated-by: checkpoint-step-mirror"
        assert src.count(needle) == 2, \
            "last_saved_step dedup annotations moved — update this " \
            "surgery"
        vs = lint_source(src.replace(needle, "#"),
                         path="bigdl_tpu/optim/optimizer.py")
        hits = [v for v in vs if v.rule == "GL401"]
        assert len(hits) >= 2
        assert all("one-sided" in v.message for v in hits)

    def test_fast_forward_divisibility_revert_is_caught(self):
        # the PR-16 bug: floored per-host skip without the divisibility
        # assert mis-positions hosts after an elastic resume.  Removing
        # the guard must fire GL404 at the fast_forward_records feed.
        src = open(os.path.join(REPO, "bigdl_tpu", "optim",
                                "optimizer.py")).read()
        guard = (
            "        if rec % scale:\n"
            "            raise ValueError(\n"
            '                f"mid-epoch resume: the snapshot\'s global '
            'records "\n'
            '                f"counter ({rec}) does not divide by this '
            'run\'s records "\n'
            '                f"scale ({scale}) — the world size/process '
            'count "\n'
            '                f"changed since the snapshot was written '
            'and the "\n'
            '                f"per-host skip would mis-position the '
            'dataset; resume "\n'
            '                f"at a compatible scale or from an epoch '
            'boundary")\n')
        assert guard in src, \
            "_fast_forward guard moved — update this surgery"
        vs = lint_source(src.replace(guard, ""),
                         path="bigdl_tpu/optim/optimizer.py")
        hits = [v for v in vs if v.rule == "GL404"]
        assert len(hits) == 1
        assert "fast_forward_records" in hits[0].message

    def test_schema_bucket_content_revert_is_caught(self):
        # dropping the world-size-invariant fingerprint from the
        # checkpoint schema (the PR-16 elastic-resume contract) fires
        # GL402 on the real build_schema call
        src = open(os.path.join(REPO, "bigdl_tpu", "optim",
                                "distri_optimizer.py")).read()
        kwarg = (",\n            bucket_content="
                 "grad_sync.bucket_content_sizes(self._gs_plan))")
        assert kwarg in src, \
            "_checkpoint_schema call moved — update this surgery"
        vs = lint_source(src.replace(kwarg, ")"),
                         path="bigdl_tpu/optim/distri_optimizer.py")
        hits = [v for v in vs if v.rule == "GL402"]
        assert len(hits) == 1
        assert "bucket_content" in hits[0].message

    def test_shipped_sources_lint_clean(self):
        # the gate cuts both ways: with every fix and annotation in
        # place the real files carry zero GL4xx findings
        for rel in ("bigdl_tpu/optim/optimizer.py",
                    "bigdl_tpu/optim/distri_optimizer.py"):
            src = open(os.path.join(REPO, *rel.split("/"))).read()
            vs = [v for v in lint_source(src, path=rel)
                  if v.rule.startswith("GL4")]
            assert vs == [], "\n".join(v.render() for v in vs)


# ===========================================================================
# rule catalog invariants
# ===========================================================================
class TestCatalog:
    def test_every_rule_registered_with_metadata(self):
        rules = all_rules()
        assert len(rules) >= 13
        ids = [r.id for r in rules]
        assert ids == sorted(ids)
        for r in rules:
            assert r.id.startswith("GL") and r.name and r.description
            assert r.severity in ("error", "warning")

    def test_this_file_covers_every_rule_positively(self):
        # the acceptance criterion, enforced mechanically: each rule id
        # appears in at least one positive assertion above
        src = open(os.path.abspath(__file__)).read()
        for r in all_rules():
            assert f'"{r.id}"' in src, f"no test mentions {r.id}"


# ===========================================================================
# suppressions
# ===========================================================================
SEEDED = """\
import numpy as np

def init(shape):
    return np.random.normal(0, 1, shape)
"""


class TestSuppressions:
    def test_trailing_suppresses_that_line_only(self):
        src = ("import numpy as np\n"
               "A = np.zeros(3, dtype=np.float64)"
               "  # graftlint: disable=GL104\n"
               "B = np.zeros(3, dtype=np.float64)\n")
        vs = lint_source(src, path=LIB)
        assert [(v.rule, v.line) for v in vs] == [("GL104", 3)]

    def test_standalone_comment_suppresses_next_statement_only(self):
        src = ("import numpy as np\n"
               "# host-side precompute  graftlint: disable=GL104\n"
               "A = np.zeros(3, dtype=np.float64)\n"
               "B = np.zeros(3, dtype=np.float64)\n")
        vs = lint_source(src, path=LIB)
        assert [(v.rule, v.line) for v in vs] == [("GL104", 4)]

    def test_standalone_comment_skips_continuation_comments(self):
        # a justification block may continue below the directive; the
        # suppression lands on the next STATEMENT, not the next line
        src = ("import numpy as np\n"
               "# graftlint: disable=GL104\n"
               "# (simplex precompute, cast to f32 at the use site)\n"
               "\n"
               "A = np.zeros(3, dtype=np.float64)\n"
               "B = np.zeros(3, dtype=np.float64)\n")
        vs = lint_source(src, path=LIB)
        assert [(v.rule, v.line) for v in vs] == [("GL104", 6)]

    def test_file_level_disable(self):
        src = ("# graftlint: disable-file=GL105\n" + SEEDED)
        assert lint_source(src, path=LIB) == []

    def test_wrong_rule_id_does_not_suppress(self):
        src = ("# graftlint: disable-file=GL104\n" + SEEDED)
        assert [v.rule for v in lint_source(src, path=LIB)] == ["GL105"]

    def test_rule_name_accepted_as_alias(self):
        src = ("# graftlint: disable-file=nondeterministic-rng\n" + SEEDED)
        assert lint_source(src, path=LIB) == []

    def test_respect_suppressions_false_surfaces_everything(self):
        src = ("# graftlint: disable-file=GL105\n" + SEEDED)
        vs = lint_source(src, path=LIB, respect_suppressions=False)
        assert [v.rule for v in vs] == ["GL105"]


# ===========================================================================
# drivers: JSON schema, CLI exit codes, --changed-only
# ===========================================================================
def run_cli(*args, cwd=REPO):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        capture_output=True, text=True, cwd=cwd, env=env)


class TestCLI:
    def test_seeded_violation_nonzero_exit_with_rule_and_location(
            self, tmp_path):
        bad = tmp_path / "bigdl_tpu" / "nn"
        bad.mkdir(parents=True)
        f = bad / "seeded.py"
        f.write_text(SEEDED)
        r = run_cli(str(f))
        assert r.returncode == 1
        assert "GL105" in r.stdout
        assert "seeded.py:4" in r.stdout  # file:line

    def test_clean_file_exits_zero(self, tmp_path):
        f = tmp_path / "clean.py"
        f.write_text("x = 1\n")
        r = run_cli(str(f))
        assert r.returncode == 0

    def test_missing_path_usage_error(self):
        r = run_cli("definitely/not/a/path.py")
        assert r.returncode == 2

    def test_json_schema(self, tmp_path):
        bad = tmp_path / "bigdl_tpu"
        bad.mkdir()
        (bad / "seeded.py").write_text(SEEDED)
        r = run_cli("--json", str(bad))
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["version"] == JSON_SCHEMA_VERSION
        assert doc["tool"] == "graftlint"
        assert doc["files_scanned"] == 1
        assert doc["counts"] == {"error": 1, "warning": 0}
        (v,) = doc["violations"]
        assert set(v) == {"rule", "name", "severity", "path", "line",
                          "col", "message"}
        assert v["rule"] == "GL105" and v["line"] == 4
        assert v["severity"] == "error"

    def test_select_restricts_rules(self, tmp_path):
        f = tmp_path / "bigdl_tpu_mod.py"
        f.write_text("import numpy as np\n"
                     "A = np.zeros(3, dtype=np.float64)\n"
                     "B = np.random.rand(3)\n")
        r = run_cli("--json", "--select", "GL104", str(f))
        doc = json.loads(r.stdout)
        assert {v["rule"] for v in doc["violations"]} == {"GL104"}

    def test_list_rules_covers_catalog(self):
        r = run_cli("--list-rules")
        assert r.returncode == 0
        for rule in all_rules():
            assert rule.id in r.stdout

    def test_syntax_error_reported_not_crash(self, tmp_path):
        f = tmp_path / "bigdl_tpu_broken.py"
        f.write_text("def broken(:\n")
        r = run_cli(str(f))
        assert r.returncode == 1
        assert "GL000" in r.stdout


class TestSarifOutput:
    def test_sarif_schema_and_location(self, tmp_path):
        bad = tmp_path / "bigdl_tpu"
        bad.mkdir()
        (bad / "seeded.py").write_text(SEEDED)
        r = run_cli("--format", "sarif", str(bad))
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["version"] == "2.1.0"
        assert "sarif-schema-2.1.0" in doc["$schema"]
        (run,) = doc["runs"]
        driver = run["tool"]["driver"]
        assert driver["name"] == "graftlint"
        rule_ids_in_driver = [ru["id"] for ru in driver["rules"]]
        for rule in all_rules():
            assert rule.id in rule_ids_in_driver
        (res,) = run["results"]
        assert res["ruleId"] == "GL105"
        assert res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("seeded.py")
        assert loc["region"]["startLine"] == 4
        assert loc["region"]["startColumn"] >= 1
        # results reference the driver rules by index
        assert rule_ids_in_driver[res["ruleIndex"]] == "GL105"

    def test_sarif_clean_run_has_empty_results(self, tmp_path):
        f = tmp_path / "clean.py"
        f.write_text("x = 1\n")
        r = run_cli("--format", "sarif", str(f))
        assert r.returncode == 0
        doc = json.loads(r.stdout)
        assert doc["runs"][0]["results"] == []

    def test_sarif_covers_gl3xx_with_rule_metadata(self, tmp_path):
        # ISSUE-15 satellite: CI annotations must stay complete — the
        # new family ships in tool.driver.rules and results link back
        # by ruleIndex
        wire = tmp_path / "frontend"
        wire.mkdir()
        f = wire / "srv.py"
        f.write_text(
            "class H:\n"
            "    def parse(self, body):\n"
            "        try:\n"
            "            return self.decode(body)\n"
            "        except Exception as e:\n"
            "            raise _HTTPError(400, str(e))\n")
        r = run_cli("--format", "sarif", str(f))
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        driver = doc["runs"][0]["tool"]["driver"]
        ids = [rule["id"] for rule in driver["rules"]]
        for rid in ("GL301", "GL302", "GL303"):
            assert rid in ids
            meta = driver["rules"][ids.index(rid)]
            assert meta["shortDescription"]["text"]
            assert meta["defaultConfiguration"]["level"] == "error"
        (res,) = doc["runs"][0]["results"]
        assert res["ruleId"] == "GL302"
        assert driver["rules"][res["ruleIndex"]]["id"] == "GL302"

    def test_json_flag_still_emits_graftlint_schema(self, tmp_path):
        # --json stays the graftlint schema (alias of --format json);
        # mixing it with a different --format is a usage error
        f = tmp_path / "clean.py"
        f.write_text("x = 1\n")
        r = run_cli("--json", "--format", "sarif", str(f))
        assert r.returncode == 2


FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")


class TestSarifFixture:
    """ISSUE-17 satellite: the SARIF emitter is pinned by a checked-in
    fixture (known source, known findings, known lines) and validated
    against a vendored subset of the SARIF 2.1.0 schema — CI's PR
    annotations must not drift silently."""

    def _emit(self, tmp_path):
        lib = tmp_path / "bigdl_tpu" / "parallel"
        lib.mkdir(parents=True)
        src = open(os.path.join(FIXTURES, "sarif_fixture.py")).read()
        (lib / "sarif_fixture.py").write_text(src)
        r = run_cli("--format", "sarif", str(lib / "sarif_fixture.py"))
        assert r.returncode == 1
        return json.loads(r.stdout)

    def test_fixture_output_matches_expected_results(self, tmp_path):
        doc = self._emit(tmp_path)
        got = [{
            "ruleId": res["ruleId"],
            "level": res["level"],
            "uri": os.path.basename(
                res["locations"][0]["physicalLocation"]
                ["artifactLocation"]["uri"]),
            "startLine": res["locations"][0]["physicalLocation"]
                            ["region"]["startLine"],
            "startColumn": res["locations"][0]["physicalLocation"]
                              ["region"]["startColumn"],
        } for res in doc["runs"][0]["results"]]
        expected = json.load(open(os.path.join(
            FIXTURES, "sarif_fixture.expected.json")))["results"]
        assert got == expected, (
            "SARIF output drifted from the checked-in fixture — if the "
            "change is intentional, regenerate "
            "tests/fixtures/graftlint/sarif_fixture.expected.json")

    def test_fixture_output_validates_against_sarif_schema(self,
                                                           tmp_path):
        jsonschema = pytest.importorskip("jsonschema")
        doc = self._emit(tmp_path)
        schema = json.load(open(os.path.join(
            FIXTURES, "sarif-2.1.0-subset.schema.json")))
        jsonschema.validate(doc, schema)
        # ruleIndex must point at the matching driver rule
        rules = doc["runs"][0]["tool"]["driver"]["rules"]
        for res in doc["runs"][0]["results"]:
            assert rules[res["ruleIndex"]]["id"] == res["ruleId"]

    def test_lint_ci_wrapper_emits_sarif_and_stats(self, tmp_path):
        # tools/lint_ci.sh: one call → SARIF artifact + debt dashboard,
        # exit status = the lint gate's
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        out = tmp_path / "report.sarif"
        env = dict(os.environ, PYTHONPATH=REPO,
                   GRAFTLINT_SARIF_OUT=str(out), PYTHON=sys.executable)
        r = subprocess.run(
            ["sh", os.path.join(REPO, "tools", "lint_ci.sh"),
             str(clean)],
            capture_output=True, text=True, env=env)
        assert r.returncode == 0, r.stderr
        doc = json.loads(out.read_text())
        assert doc["version"] == "2.1.0"
        assert doc["runs"][0]["results"] == []
        assert "suppressed" in r.stdout  # the --stats table header
        assert "SARIF report written" in r.stderr

    def test_lint_ci_wrapper_propagates_findings_exit(self, tmp_path):
        bad = tmp_path / "bigdl_tpu"
        bad.mkdir()
        (bad / "seeded.py").write_text(SEEDED)
        out = tmp_path / "report.sarif"
        env = dict(os.environ, PYTHONPATH=REPO,
                   GRAFTLINT_SARIF_OUT=str(out), PYTHON=sys.executable)
        r = subprocess.run(
            ["sh", os.path.join(REPO, "tools", "lint_ci.sh"),
             str(bad)],
            capture_output=True, text=True, env=env)
        assert r.returncode == 1
        doc = json.loads(out.read_text())
        assert doc["runs"][0]["results"]


class TestStatsCLI:
    SRC = ("import numpy as np\n"
           "A = np.zeros(3, dtype=np.float64)"
           "  # precomputed simplex; graftlint: disable=GL104\n"
           "B = np.zeros(3, dtype=np.float64)\n"
           "C = np.random.rand(3)\n")

    def test_stats_counts_findings_and_suppressions(self, tmp_path):
        d = tmp_path / "bigdl_tpu"
        d.mkdir()
        (d / "mod.py").write_text(self.SRC)
        r = run_cli("--stats", str(d))
        assert r.returncode == 0  # stats is a dashboard, not a gate
        lines = {ln.split()[0]: ln for ln in r.stdout.splitlines()
                 if ln.startswith("GL")}
        # GL104: one live finding, one suppressed; GL105: one finding
        assert lines["GL104"].split()[-2:] == ["1", "1"]
        assert lines["GL105"].split()[-2:] == ["1", "0"]
        # every registered rule has a row (zero-debt rows included)
        for rule in all_rules():
            assert rule.id in lines

    def test_stats_json(self, tmp_path):
        d = tmp_path / "bigdl_tpu"
        d.mkdir()
        (d / "mod.py").write_text(self.SRC)
        r = run_cli("--stats", "--json", str(d))
        doc = json.loads(r.stdout)
        assert doc["files_scanned"] == 1
        assert doc["rules"]["GL104"] == {
            "name": "float64-promotion", "findings": 1, "suppressed": 1}

    def test_stats_rejects_unsupported_flag_combos(self, tmp_path):
        # review regression: --stats must refuse flags it cannot
        # honor instead of silently reporting whole-tree numbers
        f = tmp_path / "clean.py"
        f.write_text("x = 1\n")
        assert run_cli("--stats", "--changed-only",
                       str(f)).returncode == 2
        assert run_cli("--stats", "--format", "sarif",
                       str(f)).returncode == 2

    def test_stats_debt_table_deterministically_ordered(self, tmp_path):
        # ISSUE-17 satellite: the per-file debt table is sorted by
        # (rule, path) so two runs over the same tree diff clean
        d = tmp_path / "bigdl_tpu"
        d.mkdir()
        f64 = ("import numpy as np\n"
               "A = np.zeros(3, dtype=np.float64)"
               "  # reviewed; graftlint: disable=GL104\n")
        rng = ("import numpy as np\n"
               "B = np.random.rand(3)"
               "  # reviewed; graftlint: disable=GL105\n")
        (d / "zeta.py").write_text(f64)
        (d / "alpha.py").write_text(f64 + rng)
        r1 = run_cli("--stats", str(d))
        r2 = run_cli("--stats", str(d))
        assert r1.returncode == 0
        assert r1.stdout == r2.stdout  # byte-identical across runs
        lines = r1.stdout.splitlines()
        start = next(i for i, ln in enumerate(lines)
                     if ln.startswith("suppression debt by file"))
        rows = [ln.split() for ln in lines[start + 1:]
                if ln.startswith("  GL")]
        keys = [(rule, path) for rule, path, _n in rows]
        assert keys == sorted(keys)
        # both files and both rules are present, rule-major
        assert [k[0] for k in keys] == ["GL104", "GL104", "GL105"]
        assert keys[0][1].endswith("alpha.py")
        assert keys[1][1].endswith("zeta.py")

    def test_stats_debt_table_json_is_sorted_too(self, tmp_path):
        d = tmp_path / "bigdl_tpu"
        d.mkdir()
        (d / "b.py").write_text(
            "import numpy as np\n"
            "A = np.zeros(3, dtype=np.float64)"
            "  # ok; graftlint: disable=GL104\n")
        (d / "a.py").write_text(
            "import numpy as np\n"
            "A = np.zeros(3, dtype=np.float64)"
            "  # ok; graftlint: disable=GL104\n")
        r = run_cli("--stats", "--json", str(d))
        doc = json.loads(r.stdout)
        paths = list(doc["suppressions_by_file"])
        assert paths == sorted(paths)

    def test_select_prefix_runs_a_family(self, tmp_path):
        f = tmp_path / "bigdl_tpu_mod.py"
        f.write_text("import threading\n"
                     "def fire(fn):\n"
                     "    threading.Thread(target=fn).start()\n"
                     "x = __import__('numpy').random.rand(3)\n")
        r = run_cli("--json", "--select", "GL2", str(f))
        doc = json.loads(r.stdout)
        assert {v["rule"] for v in doc["violations"]} == {"GL204"}

    def test_default_paths_cover_tools_and_bench(self, tmp_path):
        # ISSUE-15 satellite: the bare CLI gate extends past bigdl_tpu
        # to tools/ and bench.py (threaded helper code is product
        # too).  Exercised against a stub tree so the default-path
        # resolution is gated end-to-end without a full-repo scan
        # (the real tree's cleanliness is TestRealTree's job).
        (tmp_path / "bigdl_tpu").mkdir()
        (tmp_path / "bigdl_tpu" / "m.py").write_text("x = 1\n")
        (tmp_path / "tools").mkdir()
        (tmp_path / "tools" / "t.py").write_text("y = 2\n")
        (tmp_path / "bench.py").write_text("z = 3\n")
        r = run_cli("--json", cwd=str(tmp_path))
        doc = json.loads(r.stdout)
        assert doc["files_scanned"] == 3


# ===========================================================================
# suppression-debt baseline (ISSUE-15 satellite)
# ===========================================================================
@pytest.fixture(scope="module")
def full_tree_scan():
    """ONE whole-tree scan (gate result + suppression stats) shared by
    every full-tree gate in this module — the scan costs ~35s on the
    CPU host and three tests used to repeat it."""
    from tools.graftlint import core
    old = os.getcwd()
    os.chdir(REPO)  # baseline keys and violation paths are repo-relative
    try:
        return core.lint_paths_with_stats(["bigdl_tpu", "tools",
                                           "bench.py"])
    finally:
        os.chdir(old)


class TestSuppressionBaseline:
    """Suppression debt can shrink silently, never grow silently: the
    checked-in ``tools/graftlint/suppressions_baseline.json`` freezes
    per-file per-rule counts; growing one requires regenerating the
    baseline (``--stats --write-baseline`` — a reviewed diff) AND a
    triage-table row in tools/graftlint/README.md."""

    def test_checked_in_baseline_loads(self):
        from tools.graftlint import core
        doc = core.load_baseline()
        assert doc["schema_version"] == core.BASELINE_SCHEMA_VERSION
        assert doc["suppressions"], "empty baseline — regenerate"

    def test_no_net_new_suppression_debt(self, full_tree_scan):
        from tools.graftlint import core
        _, stats = full_tree_scan
        delta = core.suppression_debt_delta(stats, core.load_baseline())
        assert delta == [], (
            "net-new `# graftlint: disable=` entries:\n  "
            + "\n  ".join(delta)
            + "\nEither remove the suppression, or (reviewed) "
              "regenerate the baseline with `python -m tools.graftlint "
              "--stats --write-baseline` AND add a triage-table row "
              "to tools/graftlint/README.md")

    def test_every_baseline_file_has_a_readme_triage_mention(self):
        from tools.graftlint import core
        doc = core.load_baseline()
        readme = open(os.path.join(REPO, "tools", "graftlint",
                                   "README.md")).read()
        for path, rules in sorted(doc["suppressions"].items()):
            if not any(rules.values()):
                continue
            assert os.path.basename(path) in readme, (
                f"{path} carries suppressions but has no triage row "
                "in tools/graftlint/README.md")

    def test_delta_detects_growth_and_tolerates_shrink(self):
        from tools.graftlint.core import suppression_debt_delta
        baseline = {"suppressions": {"a.py": {"GL201": 2},
                                     "b.py": {"GL104": 1}}}
        grown = {"suppressions_by_file": {"a.py": {"GL201": 3}}}
        assert suppression_debt_delta(grown, baseline) == [
            "a.py: GL201 suppressions 3 > baseline 2"]
        shrunk = {"suppressions_by_file": {"a.py": {"GL201": 1}}}
        assert suppression_debt_delta(shrunk, baseline) == []
        new_file = {"suppressions_by_file": {"c.py": {"GL302": 1}}}
        assert suppression_debt_delta(new_file, baseline) == [
            "c.py: GL302 suppressions 1 > baseline 0"]

    def test_write_baseline_cli_round_trip(self, tmp_path):
        d = tmp_path / "bigdl_tpu"
        d.mkdir()
        (d / "mod.py").write_text(
            "import numpy as np\n"
            "A = np.zeros(3, dtype=np.float64)"
            "  # reviewed; graftlint: disable=GL104\n")
        out = tmp_path / "baseline.json"
        r = run_cli("--stats", "--write-baseline", str(out), str(d),
                    cwd=str(tmp_path))
        assert r.returncode == 0
        doc = json.loads(out.read_text())
        assert doc["schema_version"] == 1
        assert doc["suppressions"] == {"bigdl_tpu/mod.py": {"GL104": 1}}

    def test_write_baseline_requires_stats(self, tmp_path):
        f = tmp_path / "clean.py"
        f.write_text("x = 1\n")
        r = run_cli("--write-baseline", str(tmp_path / "b.json"),
                    str(f))
        assert r.returncode == 2
        assert "--stats" in r.stderr


class TestChangedOnlyImportClosure:
    def test_importers_of_changed_modules_are_relinted(self, tmp_path):
        from tools.graftlint import core
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        a = pkg / "locks.py"
        a.write_text("import threading\nLOCK = threading.Lock()\n")
        b = pkg / "user_abs.py"
        b.write_text("from pkg.locks import LOCK\n")
        c = pkg / "user_rel.py"
        c.write_text("from . import locks\n")
        d = pkg / "bystander.py"
        d.write_text("x = 1\n")
        files = [str(a), str(b), str(c), str(d)]
        got = core.expand_changed_with_importers(
            files, [str(a)], root=str(tmp_path))
        assert got == [str(a), str(b), str(c)]

    def test_plain_import_reaches_ancestor_packages(self, tmp_path):
        # review regression: `import a.b.c` executes a/__init__ and
        # a/b/__init__ too, so a changed package __init__ re-lints
        # importers using the plain-import form as well
        from tools.graftlint import core
        pkg = tmp_path / "pkg"
        sub = pkg / "sub"
        sub.mkdir(parents=True)
        init = pkg / "__init__.py"
        init.write_text("")
        (sub / "__init__.py").write_text("")
        leaf = sub / "leaf.py"
        leaf.write_text("x = 1\n")
        user = tmp_path / "user.py"
        user.write_text("import pkg.sub.leaf\n")
        got = core.expand_changed_with_importers(
            [str(leaf), str(user)], [str(init)], root=str(tmp_path))
        assert got == [str(user)]

    def test_no_changes_scans_nothing(self, tmp_path):
        from tools.graftlint import core
        f = tmp_path / "m.py"
        f.write_text("x = 1\n")
        assert core.expand_changed_with_importers(
            [str(f)], [], root=str(tmp_path)) == []

    def test_module_name_of(self, tmp_path):
        from tools.graftlint import core
        root = str(tmp_path)
        assert core.module_name_of(
            str(tmp_path / "a" / "b.py"), root) == "a.b"
        assert core.module_name_of(
            str(tmp_path / "a" / "__init__.py"), root) == "a"
        assert core.module_name_of(
            str(tmp_path.parent / "outside.py"), root) is None


class TestChangedOnly:
    def test_filter_changed_intersects_normalized(self):
        files = ["bigdl_tpu/nn/module.py", "bigdl_tpu/optim/sgd.py"]
        changed = {"./bigdl_tpu/nn/module.py", "tests/test_x.py"}
        assert filter_changed(files, changed) == ["bigdl_tpu/nn/module.py"]

    def test_filter_changed_matches_absolute_targets(self):
        # lint targets may be absolute while git reports repo-relative
        # paths anchored at the toplevel — both sides resolve to abs
        files = [os.path.join(os.getcwd(), "bigdl_tpu/nn/module.py")]
        changed = {"bigdl_tpu/nn/module.py"}
        assert filter_changed(files, changed) == files

    def test_changed_only_sees_changes_with_absolute_target(self):
        # end to end against the real repo: this test file itself is
        # new/modified, so a --changed-only run over tests/ must find it
        from tools.graftlint import core
        changed = core.changed_files("HEAD")
        assert all(os.path.isabs(c) for c in changed)
        me = os.path.abspath(__file__)
        if me in changed:  # true in the PR worktree, not after merge
            got = filter_changed([me], changed)
            assert got == [me]

    def test_changed_only_with_no_matching_changes_scans_nothing(
            self, tmp_path):
        # outside any git repo state for these paths: empty scan, exit 0
        f = tmp_path / "bigdl_tpu_x.py"
        f.write_text(SEEDED)
        r = run_cli("--json", "--changed-only", "--base", "HEAD",
                    str(f), cwd=str(tmp_path))
        assert r.returncode == 0
        assert json.loads(r.stdout)["files_scanned"] == 0


# ===========================================================================
# THE GATE: the real tree is violation-free
# ===========================================================================
class TestRealTree:
    def test_bigdl_tpu_lints_clean(self, full_tree_scan):
        result, _ = full_tree_scan
        assert result.files_scanned > 50
        lib = [v for v in result.violations
               if v.path.startswith("bigdl_tpu")]
        msgs = "\n".join(v.render() for v in lib)
        assert lib == [], (
            "graftlint gate: fix the hazard or add a reviewed inline "
            "suppression with a justification:\n" + msgs)

    def test_tools_lint_clean_too(self, full_tree_scan):
        # ISSUE-15 satellite: the gate covers the tools/ tree AND
        # bench.py (threaded helper code is product code) — same bar
        # as the library: zero findings, not just zero errors
        result, _ = full_tree_scan
        rest = [v for v in result.violations
                if not v.path.startswith("bigdl_tpu")]
        msgs = "\n".join(v.render() for v in rest)
        assert rest == [], msgs

    def test_telemetry_package_lints_clean(self):
        """The telemetry package rides inside the bigdl_tpu gate above,
        but its inertness contract (host-side only — no jit-reachable
        syncs, no tensor branches) earns an explicit standalone gate:
        a regression here means telemetry code leaked into traced
        scope."""
        result = lint_paths([os.path.join(REPO, "bigdl_tpu",
                                          "telemetry")])
        assert result.files_scanned >= 5
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_ops_package_lints_clean(self):
        """Standalone gate for the custom-kernel modules (round-10,
        ISSUE-8): ops/ holds pallas kernel bodies plus their
        supported()/impl gating — all kernel-choice branching must be
        host-static (shape/dtype/config), never tensor-valued, and
        kernel wrappers must stay sync-free.  A violation here means a
        kernel gate leaked into traced scope (see the catalog note
        "kernel gating is host code")."""
        result = lint_paths([os.path.join(REPO, "bigdl_tpu", "ops")])
        assert result.files_scanned >= 5  # incl. pallas_int8_gemm.py
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_int8_gemm_modules_lint_clean(self):
        """Standalone gate for the int8 speed path (the quantized
        inference PR): the GEMM wrapper's mode/impl/supported() gating
        and the quantized layers' GEMM-engagement checks
        (``_gemm_engages``) are host code by the same contract as every
        kernel gate — static shape/dtype/config facts only (catalog
        note "int8 kernel gating is host code").  A violation here
        means quantization dispatch grew a tensor-valued branch or a
        traced-scope sync."""
        result = lint_paths([
            os.path.join(REPO, "bigdl_tpu", "ops",
                         "pallas_int8_gemm.py"),
            os.path.join(REPO, "bigdl_tpu", "nn", "quantized.py")])
        assert result.files_scanned == 2
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_autotuner_lints_clean(self):
        """Standalone gate for the autotuner (round-11, ISSUE-9):
        tools/autotune.py is pure host-side search/driver code — every
        measurement rides bench._measure or the serving engine, so any
        traced-scope hazard surfacing here means search code leaked
        into a jit.  utils/tuned.py (the consumption side) rides the
        bigdl_tpu gate above but is host-side-only by the same
        contract, so it gets the explicit gate too."""
        result = lint_paths([os.path.join(REPO, "tools", "autotune.py"),
                             os.path.join(REPO, "bigdl_tpu", "utils",
                                          "tuned.py")])
        assert result.files_scanned == 2
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_resilience_package_lints_clean(self):
        """Standalone gate for the resilience package (ISSUE-10): the
        fault injector, health state machines and ReplicaSet router are
        pure host-side bookkeeping (threads, locks, clocks — no jax in
        the hot path), and the numeric guard's device half lives in
        optim/ riding the replay fetch (catalog note "the numeric guard
        rides the replay boundary").  A violation here means resilience
        code grew a traced-scope sync or tensor branch — exactly the
        hazard a recovery path must never add to the driver."""
        result = lint_paths([os.path.join(REPO, "bigdl_tpu",
                                          "resilience")])
        assert result.files_scanned >= 5
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_frontend_package_lints_clean(self):
        """Standalone gate for the wire frontend (ISSUE-14): the HTTP
        server, QoS admission, hot cutover and autoscaler are pure
        host-side plumbing (stdlib http.server threads, token buckets,
        condition-waited drain counters — no jax import anywhere in
        the package), and the new threaded modules carry
        `# guarded-by:` annotations from day one.  GL1xx and GL2xx
        both run here; a violation means the wire plane grew either a
        traced-scope hazard or an unguarded-shared-state regression.
        ISSUE-19 adds the event-loop core (eventloop.py, http1.py):
        loop-owned state rides the documented single-owner discipline,
        cross-thread handoffs stay lock-guarded."""
        result = lint_paths([os.path.join(REPO, "bigdl_tpu",
                                          "frontend")])
        assert result.files_scanned == 7
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_frontend_package_clean_under_gl2_select(self):
        """The concurrency family alone over the frontend package —
        the `--select GL2` gate ISSUE-14 names for the new threaded
        modules (wire inflight counters, scale locks, controller
        state)."""
        result = lint_paths([os.path.join(REPO, "bigdl_tpu",
                                          "frontend")],
                            select=["GL2"])
        assert result.files_scanned == 7
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_decode_serving_modules_lint_clean(self):
        """Standalone gate for the sharded-serving + continuous-
        batching modules (ISSUE-20): serving/sharded.py is pure
        host-side placement plumbing (device grouping, per-slot mesh
        construction — its one jax surface is the off-path
        ``device_put`` warmup in ``_build_replica``), and
        serving/decode.py holds the GL106 discipline at decode
        granularity — every prefill bucket, the cache splice and the
        step executable AOT-compile in the constructor, so a
        steady-state retrace or a traced-scope sync here means the
        iteration scheduler regressed into trace-per-request."""
        result = lint_paths([
            os.path.join(REPO, "bigdl_tpu", "serving", "sharded.py"),
            os.path.join(REPO, "bigdl_tpu", "serving", "decode.py")])
        assert result.files_scanned == 2
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_decode_serving_modules_clean_under_gl2_select(self):
        """The concurrency family alone over the two ISSUE-20 modules
        — the decode scheduler's cross-thread surface (queue,
        lifecycle flags, active count) carries `# guarded-by: _cond`
        contracts from day one; the slot bookkeeping and device caches
        are single-owner (the scheduler thread) by the module's
        documented thread model."""
        result = lint_paths([
            os.path.join(REPO, "bigdl_tpu", "serving", "sharded.py"),
            os.path.join(REPO, "bigdl_tpu", "serving", "decode.py")],
            select=["GL2"])
        assert result.files_scanned == 2
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_obs_plane_modules_lint_clean(self):
        """Standalone gate for the observability round-2 surface
        (ISSUE-11): the admin plane, flight recorder and request
        context are pure host-side plumbing (http.server thread,
        JSONL stream, id minting — no jax anywhere near a hot path),
        and the two reporting tools are offline file-joiners.  A
        violation here means observability code grew a traced-scope
        hazard — exactly what the "events ride existing boundaries"
        catalog note forbids."""
        result = lint_paths([
            os.path.join(REPO, "bigdl_tpu", "telemetry", "admin.py"),
            os.path.join(REPO, "bigdl_tpu", "telemetry", "flight.py"),
            os.path.join(REPO, "bigdl_tpu", "telemetry", "context.py"),
            os.path.join(REPO, "tools", "obs_report.py"),
            os.path.join(REPO, "tools", "trace_report.py"),
        ])
        assert result.files_scanned == 5
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_threaded_packages_clean_under_gl2_select(self):
        """Standalone concurrency gate (ISSUE-13): the threaded
        serving/resilience/telemetry/checkpoint plane must hold its
        documented locking contracts under the GL2xx family alone —
        `# guarded-by:` annotations honored, no non-reentrant
        re-takes, settle-every-path, thread lifecycle, wait
        predicates, no blocking under locks.  A violation here is a
        regression of exactly the bug classes the PR 5/10/11 review
        rounds kept finding by repro."""
        result = lint_paths(
            [os.path.join(REPO, "bigdl_tpu", p)
             for p in ("serving", "resilience", "telemetry",
                       "checkpoint", "frontend")],
            select=["GL2"])
        assert result.files_scanned >= 23
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs

    def test_guarded_by_annotations_are_bound(self):
        """The annotation rollout is real, not cosmetic: the thread
        model must bind `# guarded-by:` declarations in the core
        threaded classes (a silently-unparsed annotation would turn
        GL201 into a no-op)."""
        import ast as _ast

        from tools.graftlint import threads as _threads
        expect = {
            ("bigdl_tpu/serving/batcher.py", "RequestBatcher", "_q"),
            ("bigdl_tpu/serving/registry.py", "ModelRegistry",
             "_services"),
            ("bigdl_tpu/resilience/replica_set.py", "ReplicaSet",
             "_inflight"),
            ("bigdl_tpu/resilience/health.py", "ReplicaHealth",
             "_probe_inflight"),
            ("bigdl_tpu/resilience/membership.py", "ClusterMembership",
             "_epochs"),
            ("bigdl_tpu/telemetry/registry.py", "MetricRegistry",
             "_metrics"),
            ("bigdl_tpu/telemetry/tracer.py", "Tracer", "_events"),
        }
        for rel, cls, attr in sorted(expect):
            src = open(os.path.join(REPO, rel)).read()
            model = _threads.ThreadModel(_ast.parse(src), src, rel)
            guards = model.guards_for(cls)
            assert attr in guards, f"{rel}: {cls}.{attr} unbound"

    def test_resource_annotations_are_bound(self):
        """The GL3xx rollout is real, not cosmetic: the resource model
        must bind the `# acquires:`/`# releases:` declarations in the
        core threaded modules (a silently-unparsed annotation would
        turn GL301/GL303 into no-ops — same gate as guarded-by)."""
        import ast as _ast

        from tools.graftlint import resources as _resources
        expect = {
            # path -> (resource, must-be-in-def-acquires-names)
            "bigdl_tpu/frontend/server.py": (
                "wire_inflight", {"enter", "_resolve_pinned"},
                {"exit"}),
            "bigdl_tpu/serving/batcher.py": ("queue_rows", set(),
                                             set()),
            "bigdl_tpu/resilience/health.py": ("probe_slot", set(),
                                               set()),
            "bigdl_tpu/resilience/replica_set.py": ("rs_inflight",
                                                    set(), set()),
            "bigdl_tpu/serving/registry.py": ("deploy_reservation",
                                              set(), set()),
            # ISSUE-16 satellite: the latest_valid() GC pin must hold
            # until restore_into finishes applying the snapshot
            "bigdl_tpu/checkpoint/manager.py": (
                "snapshot_pin", {"latest_valid", "restore"},
                {"unpin"}),
        }
        for rel, (res, acq_defs, rel_defs) in sorted(expect.items()):
            src = open(os.path.join(REPO, rel)).read()
            model = _resources.ResourceModel(_ast.parse(src), src, rel)
            acquired = {r for _l, toks in model.acquire_stmt_sites()
                        for r in toks}
            for toks in model.name_acquires.values():
                acquired |= toks
            released = {r for _l, toks in model.release_stmt_sites()
                        for r in toks}
            for toks in model.name_releases.values():
                released |= toks
            assert res in acquired, f"{rel}: {res} acquire unbound"
            assert res in released, f"{rel}: {res} release unbound"
            for name in acq_defs:
                assert res in model.name_acquires.get(name, set()), \
                    f"{rel}: def {name} missing `# acquires: {res}`"
            for name in rel_defs:
                assert res in model.name_releases.get(name, set()), \
                    f"{rel}: def {name} missing `# releases: {res}`"

    def test_checkpoint_package_lints_clean(self):
        """Same standalone discipline for the checkpoint package: its
        one device fetch (snapshot.capture_to_host) is only legal at
        the driver's replay boundary (catalog note "snapshot fetches
        ride the replay boundary") and everything else is host-side
        file I/O — a violation here means checkpoint code grew a
        traced-scope sync or a fetch outside that boundary."""
        result = lint_paths([os.path.join(REPO, "bigdl_tpu",
                                          "checkpoint")])
        assert result.files_scanned >= 5
        msgs = "\n".join(v.render() for v in result.violations)
        assert result.violations == [], msgs


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
