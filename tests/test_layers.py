"""Per-layer unit tests (reference: ``TEST/nn/`` — one Spec per layer,
deterministic seeds, numeric gradient checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn


def rng(i=0):
    return jax.random.PRNGKey(i)


class TestLinear:
    def test_forward_shape_and_value(self):
        layer = nn.Linear(4, 3).initialize(0)
        x = jnp.ones((2, 4))
        y = layer.forward(x)
        assert y.shape == (2, 3)
        w, b = layer._params["weight"], layer._params["bias"]
        np.testing.assert_allclose(y, x @ w.T + b, rtol=1e-6)

    def test_no_bias(self):
        layer = nn.Linear(4, 3, with_bias=False).initialize(0)
        assert "bias" not in layer._params

    def test_grad_matches_numeric(self):
        layer = nn.Linear(3, 2).initialize(1)
        x = jax.random.normal(rng(2), (5, 3))

        def loss(params):
            y, _ = layer.apply(params, {}, x)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(layer._params)
        # numeric check on one weight element
        eps = 1e-3
        p0 = layer._params
        pp = jax.tree_util.tree_map(lambda a: a.copy(), p0)
        pp["weight"] = pp["weight"].at[0, 0].add(eps)
        pm = jax.tree_util.tree_map(lambda a: a.copy(), p0)
        pm["weight"] = pm["weight"].at[0, 0].add(-eps)
        num = (loss(pp) - loss(pm)) / (2 * eps)
        np.testing.assert_allclose(g["weight"][0, 0], num, rtol=1e-2)


class TestConv:
    def test_shapes(self):
        conv = nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1).initialize(0)
        y = conv.forward(jnp.ones((2, 3, 16, 16)))
        assert y.shape == (2, 8, 16, 16)

    def test_stride(self):
        conv = nn.SpatialConvolution(3, 8, 3, 3, stride_w=2, stride_h=2).initialize(0)
        y = conv.forward(jnp.ones((2, 3, 17, 17)))
        assert y.shape == (2, 8, 8, 8)

    def test_groups(self):
        conv = nn.SpatialConvolution(4, 8, 3, 3, n_group=2).initialize(0)
        assert conv._params["weight"].shape == (8, 2, 3, 3)
        y = conv.forward(jnp.ones((1, 4, 8, 8)))
        assert y.shape == (1, 8, 6, 6)

    def test_known_value(self):
        conv = nn.SpatialConvolution(1, 1, 2, 2, with_bias=False).initialize(0)
        conv._params["weight"] = jnp.ones((1, 1, 2, 2))
        x = jnp.arange(9.0).reshape(1, 1, 3, 3)
        y = conv.forward(x)
        np.testing.assert_allclose(y[0, 0], jnp.array([[8., 12.], [20., 24.]]))

    def test_nhwc(self):
        conv = nn.SpatialConvolution(3, 8, 3, 3, format="NHWC").initialize(0)
        y = conv.forward(jnp.ones((2, 16, 16, 3)))
        assert y.shape == (2, 14, 14, 8)


class TestPooling:
    def test_max(self):
        pool = nn.SpatialMaxPooling(2, 2)
        x = jnp.arange(16.0).reshape(1, 1, 4, 4)
        y = pool.forward(x)
        np.testing.assert_allclose(y[0, 0], jnp.array([[5., 7.], [13., 15.]]))

    def test_avg(self):
        pool = nn.SpatialAveragePooling(2, 2)
        x = jnp.arange(16.0).reshape(1, 1, 4, 4)
        y = pool.forward(x)
        np.testing.assert_allclose(y[0, 0], jnp.array([[2.5, 4.5], [10.5, 12.5]]))

    def test_ceil_mode(self):
        pool = nn.SpatialMaxPooling(2, 2, ceil_mode=True)
        y = pool.forward(jnp.ones((1, 1, 5, 5)))
        assert y.shape == (1, 1, 3, 3)
        floor = nn.SpatialMaxPooling(2, 2).forward(jnp.ones((1, 1, 5, 5)))
        assert floor.shape == (1, 1, 2, 2)


class TestBatchNorm:
    def test_normalizes(self):
        bn = nn.SpatialBatchNormalization(4).initialize(0)
        x = jax.random.normal(rng(0), (8, 4, 5, 5)) * 3 + 2
        y = bn.forward(x)
        assert abs(float(jnp.mean(y))) < 1e-4
        assert abs(float(jnp.std(y)) - 1.0) < 1e-2

    def test_running_stats_updated(self):
        bn = nn.SpatialBatchNormalization(4).initialize(0)
        x = jax.random.normal(rng(1), (8, 4, 5, 5)) + 5.0
        bn.forward(x)
        assert float(jnp.mean(bn._state["running_mean"])) > 0.1

    def test_eval_uses_running(self):
        bn = nn.SpatialBatchNormalization(4).initialize(0)
        x = jax.random.normal(rng(2), (8, 4, 5, 5)) + 5.0
        bn.forward(x)
        bn.evaluate()
        y = bn.forward(x)
        # eval-mode output should NOT be zero-mean (running stats lag)
        assert abs(float(jnp.mean(y))) > 0.1


class TestDropout:
    def test_train_drops_and_scales(self):
        d = nn.Dropout(0.5)
        x = jnp.ones((100, 100))
        y = d.forward(x, rng=rng(0))
        frac_zero = float(jnp.mean(y == 0.0))
        assert 0.4 < frac_zero < 0.6
        nz = y[y != 0]
        np.testing.assert_allclose(nz, 2.0)

    def test_eval_identity(self):
        d = nn.Dropout(0.5).evaluate()
        x = jnp.ones((10, 10))
        np.testing.assert_allclose(d.forward(x), x)


class TestActivations:
    @pytest.mark.parametrize("layer,fn", [
        (nn.ReLU(), lambda x: np.maximum(x, 0)),
        (nn.Tanh(), np.tanh),
        (nn.Sigmoid(), lambda x: 1 / (1 + np.exp(-x))),
        (nn.ReLU6(), lambda x: np.clip(x, 0, 6)),
        (nn.SoftSign(), lambda x: x / (1 + np.abs(x))),
    ])
    def test_matches_numpy(self, layer, fn):
        x = np.linspace(-3, 8, 23).astype(np.float32)
        y = layer.forward(jnp.asarray(x))
        np.testing.assert_allclose(y, fn(x), rtol=1e-5, atol=1e-6)

    def test_logsoftmax_rows_sum_to_one(self):
        y = nn.LogSoftMax().forward(jax.random.normal(rng(0), (4, 7)))
        np.testing.assert_allclose(jnp.sum(jnp.exp(y), -1), 1.0, rtol=1e-5)

    def test_prelu_learnable(self):
        p = nn.PReLU().initialize(0)
        y = p.forward(jnp.array([-2.0, 3.0]))
        np.testing.assert_allclose(y, [-0.5, 3.0])


class TestContainers:
    def test_sequential(self):
        m = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU()).add(nn.Linear(8, 2))
        m.initialize(0)
        y = m.forward(jnp.ones((3, 4)))
        assert y.shape == (3, 2)

    def test_concat_table_parallel_table(self):
        ct = nn.ConcatTable().add(nn.Identity()).add(nn.Identity())
        ct.initialize(0)
        out = ct.forward(jnp.ones((2, 3)))
        assert len(out) == 2
        pt = nn.ParallelTable().add(nn.Linear(3, 4)).add(nn.Identity())
        pt.initialize(0)
        y = pt.forward((jnp.ones((2, 3)), jnp.zeros((2, 5))))
        assert y[0].shape == (2, 4) and y[1].shape == (2, 5)

    def test_concat_dim(self):
        c = nn.Concat(1).add(nn.Linear(3, 4)).add(nn.Linear(3, 6))
        c.initialize(0)
        assert c.forward(jnp.ones((2, 3))).shape == (2, 10)

    def test_caddtable_resnet_shortcut(self):
        block = nn.Sequential() \
            .add(nn.ConcatTable().add(nn.Linear(4, 4)).add(nn.Identity())) \
            .add(nn.CAddTable())
        block.initialize(0)
        assert block.forward(jnp.ones((2, 4))).shape == (2, 4)


class TestShapeOps:
    def test_reshape_view(self):
        assert nn.Reshape((2, 2)).forward(jnp.ones((3, 4))).shape == (3, 2, 2)

    def test_narrow_select(self):
        x = jnp.arange(24.0).reshape(2, 3, 4)
        assert nn.Narrow(1, 1, 2).forward(x).shape == (2, 2, 4)
        assert nn.Select(1, 0).forward(x).shape == (2, 4)

    def test_join_split_roundtrip(self):
        x = jnp.arange(12.0).reshape(2, 2, 3)
        parts = nn.SplitTable(1).forward(x)
        assert len(parts) == 2 and parts[0].shape == (2, 3)
        back = nn.Pack(1).forward(parts)
        np.testing.assert_allclose(back, x)

    def test_lookup_table(self):
        lt = nn.LookupTable(10, 4).initialize(0)
        y = lt.forward(jnp.array([[0, 3], [9, 1]]))
        assert y.shape == (2, 2, 4)

    def test_lrn_runs(self):
        y = nn.SpatialCrossMapLRN(5).forward(jnp.ones((1, 8, 4, 4)))
        assert y.shape == (1, 8, 4, 4)


class TestEagerBackward:
    def test_module_backward_accumulates(self):
        m = nn.Linear(3, 2).initialize(0)
        x = jnp.ones((4, 3))
        y = m.forward(x)
        gi = m.backward(x, jnp.ones_like(y))
        assert gi.shape == x.shape
        _, grads = m.parameters()
        assert float(jnp.sum(jnp.abs(grads["weight"]))) > 0
        m.zero_grad_parameters()
        _, grads = m.parameters()
        assert float(jnp.sum(jnp.abs(grads["weight"]))) == 0.0

    def test_flat_parameters(self):
        m = nn.Sequential().add(nn.Linear(3, 2)).add(nn.Linear(2, 1))
        flat, unravel = m.get_parameters()
        assert flat.shape == (3 * 2 + 2 + 2 * 1 + 1,)
        back = unravel(flat)
        assert back["0"]["weight"].shape == (2, 3)


class TestFullConvolution:
    def test_shape_and_channels(self):
        # output size = (in-1)*stride - 2*pad + kernel + adj
        dc = nn.SpatialFullConvolution(3, 5, 3, 3, stride_w=2, stride_h=2,
                                       pad_w=1, pad_h=1, adj_w=1, adj_h=1)
        dc.initialize(0)
        y = dc.forward(jnp.ones((2, 3, 4, 4)))
        assert y.shape == (2, 5, 8, 8)

    def test_inverts_stride2_conv_shape(self):
        x = jnp.ones((1, 4, 7, 7))
        down = nn.SpatialConvolution(4, 8, 3, 3, 2, 2, 1, 1).initialize(0)
        up = nn.SpatialFullConvolution(8, 4, 3, 3, 2, 2, 1, 1).initialize(1)
        assert up.forward(down.forward(x)).shape == (1, 4, 7, 7)

    def test_matches_manual_1d_case(self):
        # single-channel 1x1 spatial input, kernel 2, stride 2: output is
        # the kernel scaled by the input value
        dc = nn.SpatialFullConvolution(1, 1, 2, 2, 2, 2, with_bias=False)
        dc.initialize(0)
        k = jnp.arange(4.0).reshape(1, 1, 2, 2)
        dc._params["weight"] = k
        y = dc.forward(jnp.full((1, 1, 1, 1), 2.0))
        np.testing.assert_allclose(y, 2.0 * k)


class TestPoolingCeilModeEdge:
    def test_ceil_window_fully_in_padding_dropped(self):
        # kernel 2 stride 3 on size 6: ceil gives out=3 but the 3rd window
        # starts at 6 >= size+pad -> must be dropped (torch semantics)
        pool = nn.SpatialMaxPooling(2, 2, 3, 3, ceil_mode=True)
        y = pool.forward(jnp.ones((1, 1, 6, 6)))
        assert y.shape == (1, 1, 2, 2)
        assert bool(jnp.all(jnp.isfinite(y)))

    def test_ceil_avg_no_nan(self):
        pool = nn.SpatialAveragePooling(2, 2, 3, 3, ceil_mode=True,
                                        count_include_pad=False)
        y = pool.forward(jnp.ones((1, 1, 6, 6)))
        assert bool(jnp.all(jnp.isfinite(y)))
