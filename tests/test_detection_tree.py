"""Detection heads + tree LSTM tests (reference: ``TEST/nn/AnchorSpec``,
``NmsSpec``, ``PriorBoxSpec``, ``ProposalSpec``, ``RoiPoolingSpec``,
``BinaryTreeLSTMSpec``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn


class TestAnchor:
    def test_basic_anchors_centered(self):
        a = nn.Anchor(ratios=[1.0], scales=[8.0])
        # single ratio-1 scale-8 anchor on a 16-base: 128x128 centered at 7.5
        b = a.basic_anchors[0]
        assert b[2] - b[0] + 1 == 128 and b[3] - b[1] + 1 == 128
        np.testing.assert_allclose((b[0] + b[2]) / 2, 7.5)

    def test_grid_generation(self):
        a = nn.Anchor(ratios=[0.5, 1.0, 2.0], scales=[8.0, 16.0, 32.0])
        all_a = a.generate_anchors(width=4, height=3, feat_stride=16)
        assert all_a.shape == (4 * 3 * 9, 4)
        # second grid cell is shifted +16 in x
        np.testing.assert_allclose(np.asarray(all_a[9]) -
                                   np.asarray(all_a[0]),
                                   [16, 0, 16, 0])


class TestNms:
    def test_suppresses_overlaps(self):
        boxes = jnp.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                          jnp.float32)
        scores = jnp.array([0.9, 0.8, 0.7])
        idx, valid = nn.nms(boxes, scores, iou_threshold=0.5, max_output=3)
        kept = np.asarray(idx)[np.asarray(valid)]
        assert list(kept) == [0, 2]

    def test_static_shape_under_jit(self):
        f = jax.jit(lambda b, s: nn.nms(b, s, 0.5, 4))
        boxes = jnp.array([[0, 0, 5, 5]] * 8, jnp.float32)
        scores = jnp.arange(8, dtype=jnp.float32)
        idx, valid = f(boxes, scores)
        assert idx.shape == (4,) and valid.shape == (4,)
        assert int(np.asarray(valid).sum()) == 1  # all identical -> 1 kept


class TestPriorBox:
    def test_caffe_layout_and_values(self):
        pb = nn.PriorBox(min_sizes=[30.0], max_sizes=[60.0],
                         aspect_ratios=[2.0], is_flip=True,
                         variances=[0.1, 0.1, 0.2, 0.2],
                         img_h=300, img_w=300, step=8.0)
        # priors per cell: 1 (ar=1) + 2 (ar=2 + flip) + 1 (max) = 4
        assert pb.n_priors == 4
        x = jnp.zeros((1, 8, 2, 2))
        out = pb.forward(x)
        assert out.shape == (1, 2, 2 * 2 * 4 * 4)
        pr = np.asarray(out)[0, 0].reshape(2, 2, 4, 4)
        # first cell center = (0.5*8, 0.5*8); ar=1 box is min_size square
        c00 = pr[0, 0, 0]
        np.testing.assert_allclose(c00, [(4 - 15) / 300, (4 - 15) / 300,
                                         (4 + 15) / 300, (4 + 15) / 300],
                                   rtol=1e-5)
        var = np.asarray(out)[0, 1].reshape(-1, 4)
        np.testing.assert_allclose(var, np.tile([0.1, 0.1, 0.2, 0.2],
                                                (var.shape[0], 1)))


class TestProposal:
    def test_shapes_and_validity(self):
        A = 9
        H = W = 6
        rng = np.random.RandomState(0)
        scores = jnp.asarray(rng.rand(1, 2 * A, H, W).astype(np.float32))
        deltas = jnp.asarray(
            (rng.rand(1, 4 * A, H, W).astype(np.float32) - 0.5) * 0.1)
        im_info = jnp.array([[96.0, 96.0, 1.0, 1.0]])
        prop = nn.Proposal(pre_nms_topn=50, post_nms_topn=10,
                           ratios=[0.5, 1.0, 2.0], scales=[2.0, 4.0, 8.0])
        (out, valid), _ = prop.apply({}, {}, (scores, deltas, im_info))
        assert out.shape == (10, 5)
        assert np.asarray(valid).any()
        v = np.asarray(out)[np.asarray(valid)]
        # batch column zero; boxes inside the image
        assert (v[:, 0] == 0).all()
        assert (v[:, 1] >= 0).all() and (v[:, 3] <= 95).all()


class TestRoiPooling:
    def test_matches_torchvision_semantics(self):
        # hand-checkable case: 1x1x4x4 map, one RoI covering all, 2x2 pool
        data = jnp.arange(16, dtype=jnp.float32).reshape(1, 1, 4, 4)
        rois = jnp.array([[0, 0, 0, 3, 3]], jnp.float32)
        rp = nn.RoiPooling(pooled_w=2, pooled_h=2, spatial_scale=1.0)
        out, _ = rp.apply({}, {}, (data, rois))
        np.testing.assert_allclose(np.asarray(out)[0, 0],
                                   [[5, 7], [13, 15]])

    def test_batch_indexing_and_scale(self):
        rng = np.random.RandomState(1)
        data = jnp.asarray(rng.rand(2, 3, 8, 8).astype(np.float32))
        # x2=14 * scale 0.5 -> feature x2=7 -> roi width exactly 8 cells
        rois = jnp.array([[0, 0, 0, 14, 14], [1, 0, 0, 14, 14]], jnp.float32)
        rp = nn.RoiPooling(pooled_w=4, pooled_h=4, spatial_scale=0.5)
        out, _ = rp.apply({}, {}, (data, rois))
        assert out.shape == (2, 3, 4, 4)
        # full-coverage 4x4 pool of an 8x8 map = 2x2 max blocks
        expected = np.asarray(data[1, 0]).reshape(4, 2, 4, 2).max((1, 3))
        np.testing.assert_allclose(np.asarray(out)[1, 0], expected)


class TestDetectionOutputSSD:
    def test_decode_and_nms(self):
        P, C = 4, 3
        priors = np.zeros((1, 2, P * 4), np.float32)
        boxes = np.array([[0.1, 0.1, 0.3, 0.3], [0.11, 0.11, 0.31, 0.31],
                          [0.6, 0.6, 0.8, 0.8], [0.0, 0.0, 1.0, 1.0]],
                         np.float32)
        priors[0, 0] = boxes.reshape(-1)
        priors[0, 1] = np.tile([0.1, 0.1, 0.2, 0.2], P)
        loc = jnp.zeros((1, P * 4))  # zero deltas -> boxes = priors
        conf = np.full((1, P, C), 0.01, np.float32)
        conf[0, 0, 1] = 0.9   # class 1 on box 0
        conf[0, 1, 1] = 0.8   # overlapping -> suppressed
        conf[0, 2, 2] = 0.7   # class 2 on box 2
        det = nn.DetectionOutputSSD(n_classes=C, keep_topk=5,
                                    conf_thresh=0.1)
        (dets, valid), _ = det.apply(
            {}, {}, (loc, jnp.asarray(conf.reshape(1, -1)), priors))
        v = np.asarray(dets)[0][np.asarray(valid)[0]]
        assert len(v) == 2
        # sorted by score: class 1 @0.9 then class 2 @0.7
        np.testing.assert_allclose(v[0, :2], [1, 0.9], rtol=1e-5)
        np.testing.assert_allclose(v[1, :2], [2, 0.7], rtol=1e-5)
        np.testing.assert_allclose(v[0, 2:], boxes[0], atol=1e-5)


class TestBinaryTreeLSTM:
    def _simple_tree(self):
        # nodes (1-based): 1=leaf1, 2=leaf2, 3=compose(1,2)
        tree = np.array([[[0, 0, 1], [0, 0, 2], [1, 2, 0]]], np.float32)
        emb = np.random.RandomState(0).rand(1, 2, 5).astype(np.float32)
        return jnp.asarray(emb), jnp.asarray(tree)

    def test_forward_shapes_and_root(self):
        emb, tree = self._simple_tree()
        m = nn.BinaryTreeLSTM(input_size=5, hidden_size=7)
        p, s = m.init(jax.random.PRNGKey(0))
        out, _ = m.apply(p, s, (emb, tree))
        assert out.shape == (1, 3, 7)
        o = np.asarray(out)
        assert np.abs(o).sum() > 0
        # root state differs from leaves
        assert not np.allclose(o[0, 2], o[0, 0])

    def test_padding_rows_are_zero(self):
        emb, tree = self._simple_tree()
        padded = jnp.concatenate(
            [tree, jnp.zeros((1, 2, 3), tree.dtype)], axis=1)
        m = nn.BinaryTreeLSTM(5, 7)
        p, s = m.init(jax.random.PRNGKey(0))
        out, _ = m.apply(p, s, (emb, padded))
        o = np.asarray(out)
        np.testing.assert_allclose(o[0, 3:], 0.0)
        ref, _ = m.apply(p, s, (emb, tree))
        np.testing.assert_allclose(o[0, :3], np.asarray(ref)[0], rtol=1e-6)

    def test_grad_flows(self):
        emb, tree = self._simple_tree()
        m = nn.BinaryTreeLSTM(5, 7)
        p, s = m.init(jax.random.PRNGKey(0))

        def loss(p, e):
            out, _ = m.apply(p, s, (e, tree))
            return jnp.sum(out[:, -1] ** 2)

        g_p, g_e = jax.grad(loss, argnums=(0, 1))(p, emb)
        leaves = jax.tree_util.tree_leaves(g_p)
        assert any(np.abs(np.asarray(l)).sum() > 0 for l in leaves)
        assert np.abs(np.asarray(g_e)).sum() > 0

    def test_deep_tree_under_jit(self):
        # right-leaning chain of 4 leaves
        # nodes: 1..4 leaves; 5=compose(3,4); 6=compose(2,5); 7=compose(1,6)
        tree = np.array([[[0, 0, 1], [0, 0, 2], [0, 0, 3], [0, 0, 4],
                          [3, 4, 0], [2, 5, 0], [1, 6, 0]]], np.float32)
        emb = np.random.RandomState(1).rand(1, 4, 5).astype(np.float32)
        m = nn.BinaryTreeLSTM(5, 6)
        p, s = m.init(jax.random.PRNGKey(0))
        out = jax.jit(lambda p, e: m.apply(p, s, (e, jnp.asarray(tree)))[0])(
            p, jnp.asarray(emb))
        assert out.shape == (1, 7, 6)
        assert np.isfinite(np.asarray(out)).all()


class TestDetectionOutputFrcnn:
    def test_per_class_regression_and_nms(self):
        C = 3
        rois = jnp.array([[0, 10, 10, 30, 30],
                          [0, 12, 12, 32, 32],
                          [0, 60, 60, 80, 80]], jnp.float32)
        R = 3
        deltas = np.zeros((R, 4 * C), np.float32)
        # class 2 shifts box 2 by +5 in x (dx = 5/width)
        deltas[2, 8] = 5.0 / 21.0
        scores = np.full((R, C), 0.01, np.float32)
        scores[0, 1] = 0.9
        scores[1, 1] = 0.85   # overlapping with roi 0 -> suppressed
        scores[2, 2] = 0.7
        det = nn.DetectionOutputFrcnn(n_classes=C, max_per_image=6,
                                      thresh=0.05)
        im_info = jnp.array([[100.0, 100.0, 1.0, 1.0]])
        (dets, valid), _ = det.apply(
            {}, {}, (im_info, rois, jnp.asarray(deltas),
                     jnp.asarray(scores)))
        v = np.asarray(dets)[np.asarray(valid)]
        assert len(v) == 2
        np.testing.assert_allclose(v[0, :2], [1, 0.9], rtol=1e-5)
        np.testing.assert_allclose(v[1, :2], [2, 0.7], rtol=1e-5)
        # class-2 regression applied (+5 x shift on roi 2)
        np.testing.assert_allclose(v[1, 2], 65.0, atol=0.6)

    def test_static_shape_under_jit(self):
        C, R = 4, 8
        det = nn.DetectionOutputFrcnn(n_classes=C, max_per_image=9)
        f = jax.jit(lambda a, b, c, d: det.apply({}, {}, (a, b, c, d))[0])
        rng = np.random.RandomState(0)
        out, valid = f(jnp.array([[50.0, 50, 1, 1]]),
                       jnp.asarray(rng.rand(R, 5).astype(np.float32) * 40),
                       jnp.asarray((rng.rand(R, 4 * C) - 0.5).astype(
                           np.float32) * 0.1),
                       jnp.asarray(rng.rand(R, C).astype(np.float32)))
        assert out.shape == (9, 6) and valid.shape == (9,)
