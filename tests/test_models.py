"""Model-zoo smoke tests: forward shapes + one grad step per model
(reference: per-model Specs under ``TEST/`` + ``models/*/Test.scala``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import models, nn


def fwd(model, x, train=False):
    p, s = model.init(jax.random.PRNGKey(0))
    y, _ = model.apply(p, s, x, training=train,
                       rng=jax.random.PRNGKey(1) if train else None)
    return y, p, s


class TestZooShapes:
    def test_lenet(self):
        y, _, _ = fwd(models.lenet5(), jnp.ones((2, 1, 28, 28)))
        assert y.shape == (2, 10)

    def test_resnet_cifar(self):
        y, _, _ = fwd(models.resnet_cifar(20), jnp.ones((2, 3, 32, 32)))
        assert y.shape == (2, 10)

    def test_resnet50(self):
        y, _, _ = fwd(models.resnet50(), jnp.ones((1, 3, 224, 224)))
        assert y.shape == (1, 1000)

    def test_vgg_cifar(self):
        y, _, _ = fwd(models.vgg_for_cifar10(), jnp.ones((2, 3, 32, 32)))
        assert y.shape == (2, 10)

    def test_inception_v1(self):
        y, _, _ = fwd(models.inception_v1(), jnp.ones((1, 3, 224, 224)))
        assert y.shape == (1, 1000)

    def test_simple_rnn(self):
        y, _, _ = fwd(models.simple_rnn(128, 40, 128),
                      jnp.ones((2, 9, 128)))
        assert y.shape == (2, 9, 128)

    def test_ptb_model(self):
        toks = jnp.zeros((2, 12), jnp.int32)
        y, _, _ = fwd(models.ptb_model(vocab_size=50, embed_dim=16,
                                       hidden_size=16), toks)
        assert y.shape == (2, 12, 50)

    def test_autoencoder(self):
        y, _, _ = fwd(models.autoencoder(), jnp.ones((2, 1, 28, 28)))
        assert y.shape == (2, 784)


class TestZooGradients:
    @pytest.mark.parametrize("build,shape,nclass", [
        (lambda: models.resnet_cifar(20), (2, 3, 32, 32), 10),
        (lambda: models.vgg_for_cifar10(), (2, 3, 32, 32), 10),
    ])
    def test_one_grad_step_finite(self, build, shape, nclass):
        model = build()
        p, s = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), shape)
        y = jnp.zeros((shape[0],), jnp.int32)
        crit = nn.ClassNLLCriterion()

        def loss(p):
            out, _ = model.apply(p, s, x, training=True,
                                 rng=jax.random.PRNGKey(2))
            return crit.apply(out, y)

        l, g = jax.value_and_grad(loss)(p)
        assert np.isfinite(float(l))
        gn = sum(float(jnp.sum(jnp.abs(x))) for x in
                 jax.tree_util.tree_leaves(g))
        assert np.isfinite(gn) and gn > 0

    def test_param_counts_sane(self):
        # ResNet-50 ~25.5M params (torch reference)
        m = models.resnet50()
        p, _ = m.init(jax.random.PRNGKey(0))
        n = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(p))
        assert 25_000_000 < n < 26_100_000, n
        # Inception-v1 no-aux ~7M
        m = models.inception_v1()
        p, _ = m.init(jax.random.PRNGKey(0))
        n = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(p))
        assert 6_500_000 < n < 7_500_000, n


def test_inception_v2_forward():
    """BN-Inception topology (reference Inception_v2.scala no-aux):
    channel widths check out through all 10 modules."""
    from bigdl_tpu.models.inception import inception_v2
    m = inception_v2(class_num=7)
    m.initialize()
    m.training = False
    out = m.forward(np.zeros((1, 3, 224, 224), np.float32))
    assert out.shape == (1, 7)
    assert np.isfinite(np.asarray(out)).all()
    # log-softmax output sums to 1 in prob space
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(), 1.0,
                               rtol=1e-4)
