"""Graph DAG + recurrent stack tests (reference: ``TEST/nn/GraphSpec``,
``RecurrentSpec``, ``LSTMSpec``, ``GRUSpec``, …)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn


def rng(i=0):
    return jax.random.PRNGKey(i)


class TestGraph:
    def test_linear_chain_matches_sequential(self):
        inp = nn.Input()
        h = nn.Linear(4, 8)(inp)
        r = nn.ReLU()(h)
        out = nn.Linear(8, 2)(r)
        g = nn.Graph([inp], [out])
        p, s = g.init(rng(0))
        x = jax.random.normal(rng(1), (3, 4))
        y, _ = g.apply(p, s, x)
        assert y.shape == (3, 2)

    def test_diamond_dag(self):
        inp = nn.Input()
        h = nn.Linear(4, 4)(inp)
        a = nn.ReLU()(h)
        b = nn.Tanh()(h)
        out = nn.CAddTable()([a, b])
        g = nn.Graph([inp], [out])
        p, s = g.init(rng(0))
        x = jnp.ones((2, 4))
        y, _ = g.apply(p, s, x)
        # check value: relu(h)+tanh(h)
        h_v, _ = g._order[0].module.apply(p["0"], {}, x)
        np.testing.assert_allclose(y, jax.nn.relu(h_v) + jnp.tanh(h_v),
                                   rtol=1e-5)

    def test_multi_input_multi_output(self):
        i1, i2 = nn.Input(), nn.Input()
        a = nn.Linear(3, 5)(i1)
        b = nn.Linear(7, 5)(i2)
        s = nn.CAddTable()([a, b])
        m = nn.CMulTable()([a, b])
        g = nn.Graph([i1, i2], [s, m])
        p, st = g.init(rng(0))
        y, _ = g.apply(p, st, (jnp.ones((2, 3)), jnp.ones((2, 7))))
        assert y[0].shape == (2, 5) and y[1].shape == (2, 5)

    def test_cycle_detection(self):
        inp = nn.Input()
        n1 = nn.Linear(2, 2)(inp)
        n2 = nn.ReLU()(n1)
        n1.inputs.append(n2)  # introduce cycle
        with pytest.raises(ValueError, match="cycle"):
            nn.Graph([inp], [n2])

    def test_graph_under_jit_grad(self):
        inp = nn.Input()
        out = nn.Linear(4, 1)(nn.Tanh()(nn.Linear(4, 4)(inp)))
        g = nn.Graph([inp], [out])
        p, s = g.init(rng(0))
        f = jax.jit(lambda p, x: g.apply(p, s, x)[0].sum())
        gr = jax.grad(f)(p, jnp.ones((5, 4)))
        assert jax.tree_util.tree_structure(gr) == \
            jax.tree_util.tree_structure(p)


class TestCells:
    @pytest.mark.parametrize("cell_cls,hidden_tuple", [
        (nn.RnnCell, False), (nn.LSTM, True), (nn.GRU, False),
        (nn.LSTMPeephole, True),
    ])
    def test_single_step_shapes(self, cell_cls, hidden_tuple):
        cell = cell_cls(6, 10)
        p, _ = cell.init(rng(0))
        h0 = cell.initial_hidden(4)
        x = jax.random.normal(rng(1), (4, 6))
        y, h1 = cell.step(p, x, h0)
        assert y.shape == (4, 10)
        if hidden_tuple:
            assert h1[0].shape == (4, 10) and h1[1].shape == (4, 10)

    def test_lstm_gate_semantics(self):
        """All-zero params: i=f=o=0.5, g=0 → c stays 0, h=0."""
        cell = nn.LSTM(3, 4)
        p = {"weight": jnp.zeros((16, 7)), "bias": jnp.zeros((16,))}
        h0 = cell.initial_hidden(2)
        y, (h, c) = cell.step(p, jnp.ones((2, 3)), h0)
        np.testing.assert_allclose(c, 0.0)
        np.testing.assert_allclose(y, 0.0)

    def test_conv_lstm(self):
        cell = nn.ConvLSTMPeephole(2, 4, 3, spatial=(8, 8))
        p, _ = cell.init(rng(0))
        h0 = cell.initial_hidden(2)
        y, _ = cell.step(p, jnp.ones((2, 2, 8, 8)), h0)
        assert y.shape == (2, 4, 8, 8)


class TestRecurrent:
    def test_sequence_output_shape(self):
        m = nn.Recurrent(nn.LSTM(5, 7))
        p, s = m.init(rng(0))
        x = jax.random.normal(rng(1), (3, 11, 5))
        y, _ = m.apply(p, s, x)
        assert y.shape == (3, 11, 7)

    def test_scan_matches_manual_unroll(self):
        cell = nn.GRU(4, 6)
        p, _ = cell.init(rng(0))
        m = nn.Recurrent(cell)
        x = jax.random.normal(rng(1), (2, 5, 4))
        y, _ = m.apply(p, {}, x)
        # manual unroll
        h = cell.initial_hidden(2)
        outs = []
        for t in range(5):
            o, h = cell.step(p, x[:, t], h)
            outs.append(o)
        np.testing.assert_allclose(y, jnp.stack(outs, 1), rtol=2e-5,
                                   atol=1e-6)

    def test_birecurrent_concat(self):
        m = nn.BiRecurrent(nn.LSTM(4, 6))
        p, s = m.init(rng(0))
        y, _ = m.apply(p, s, jnp.ones((2, 7, 4)))
        assert y.shape == (2, 7, 12)

    def test_recurrent_decoder(self):
        m = nn.RecurrentDecoder(nn.RnnCell(6, 6), seq_length=9)
        p, s = m.init(rng(0))
        y, _ = m.apply(p, s, jnp.ones((3, 6)))
        assert y.shape == (3, 9, 6)

    def test_multi_rnn_cell_stack(self):
        stack = nn.MultiRNNCell([nn.LSTM(4, 8), nn.LSTM(8, 6)])
        m = nn.Recurrent(stack)
        p, s = m.init(rng(0))
        y, _ = m.apply(p, s, jnp.ones((2, 5, 4)))
        assert y.shape == (2, 5, 6)

    def test_time_distributed(self):
        m = nn.TimeDistributed(nn.Linear(4, 2))
        p, s = m.init(rng(0))
        y, _ = m.apply(p, s, jnp.ones((3, 7, 4)))
        assert y.shape == (3, 7, 2)

    def test_recurrent_trains(self):
        """A GRU can learn to sum a +1/-1 sequence sign."""
        model = (nn.Sequential()
                 .add(nn.Recurrent(nn.GRU(1, 16)))
                 .add(nn.Select(1, -1))  # last timestep
                 .add(nn.Linear(16, 2))
                 .add(nn.LogSoftMax()))
        p, s = model.init(rng(0))
        key = rng(42)
        x = jax.random.choice(key, jnp.array([-1.0, 1.0]), (256, 8, 1))
        y = (jnp.sum(x[:, :, 0], 1) > 0).astype(jnp.int32)
        from bigdl_tpu.nn.criterion import ClassNLLCriterion
        crit = ClassNLLCriterion()

        @jax.jit
        def step(p, x, y):
            def loss(p):
                out, _ = model.apply(p, s, x)
                return crit.apply(out, y)
            l, g = jax.value_and_grad(loss)(p)
            return jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g), l

        for _ in range(60):
            p, l = step(p, x, y)
        out, _ = model.apply(p, s, x)
        acc = float(jnp.mean(jnp.argmax(out, -1) == y))
        assert acc > 0.9, f"GRU failed to learn parity-of-sum: {acc}"


class TestReviewRegressions:
    def test_shared_module_ties_weights(self):
        """Reusing one module instance across graph positions shares params
        (reference semantics: the module owns its weights)."""
        shared = nn.Linear(4, 4)
        i1 = nn.Input()
        a = shared(i1)
        b = shared(nn.ReLU()(a))  # second use of the same instance
        g = nn.Graph([i1], [b])
        p, s = g.init(rng(0))
        # only ONE param set for the shared Linear
        linear_keys = [k for k, v in p.items() if "weight" in v]
        assert len(linear_keys) == 1
        x = jnp.ones((2, 4))
        y, _ = g.apply(p, s, x)
        w, bb = p[linear_keys[0]]["weight"], p[linear_keys[0]]["bias"]
        expected = jax.nn.relu(x @ w.T + bb) @ w.T + bb
        np.testing.assert_allclose(y, expected, rtol=1e-5)

    def test_recurrent_bf16_stays_bf16(self):
        """bf16 input must keep the whole scan in bf16 (MXU path)."""
        m = nn.Recurrent(nn.LSTM(4, 8))
        p, s = m.init(rng(0))
        p16 = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16), p)
        x = jnp.ones((2, 5, 4), jnp.bfloat16)
        y, _ = m.apply(p16, s, x)
        assert y.dtype == jnp.bfloat16

    def test_module_call_not_monkeypatched(self):
        """Node dispatch lives in Module.__call__ itself; eager call still
        works after graph import."""
        lin = nn.Linear(3, 2).initialize(0)
        y = lin(jnp.ones((1, 3)))  # eager
        assert y.shape == (1, 2)
        node = lin(nn.Input())  # graph
        assert isinstance(node, nn.Node)
