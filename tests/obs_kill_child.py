"""Subprocess child for the flight-recorder SIGKILL test
(tests/test_obs_plane.py).

Runs a 2-replica ReplicaSet with request tracing ON and a flight
recorder streaming to the path in argv[1], drives one request through
a seeded replica-death failover (so the dump contains the full victim
story: request_route → replica_death/failover → request_route → ok),
prints ``READY`` on stdout, then blocks forever — the parent SIGKILLs
it.  The point of the test: the flight recorder flushes per event, so
even a SIGKILL (no atexit, no finally, no signal handler runs) leaves
a parseable dump with the whole story on disk.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from bigdl_tpu import nn  # noqa: E402
from bigdl_tpu.resilience import ReplicaSet  # noqa: E402
from bigdl_tpu.resilience.faults import FaultInjector  # noqa: E402
from bigdl_tpu.resilience.health import HealthPolicy  # noqa: E402
from bigdl_tpu.telemetry import FlightRecorder, Tracer  # noqa: E402
from bigdl_tpu.telemetry.context import RequestContext  # noqa: E402

DIN = 8


def main():
    flight_path, trace_path = sys.argv[1], sys.argv[2]
    model = nn.Sequential(nn.Linear(DIN, 16), nn.ReLU(),
                          nn.Linear(16, 4), nn.SoftMax()).initialize(0)
    x = np.random.default_rng(0).normal(0, 1, (1, DIN)).astype(np.float32)
    flight = FlightRecorder(flight_path)
    tracer = Tracer()
    rs = ReplicaSet(
        model, n_replicas=2, input_spec=((DIN,), np.float32),
        max_batch_size=4, batch_timeout_ms=0.0, deadline_ms=0,
        fault_injector=FaultInjector("replica_death@target=0,at=0",
                                     seed=0),
        tracer=tracer, flight=flight, request_tracing=True,
        health=HealthPolicy(probe_backoff_s=0.05))
    ctx = RequestContext(tenant="kill-test")
    fut = rs.submit(x, ctx=ctx, timeout=30)
    fut.result(30)  # resolves via failover; flight has the story
    # the trace file is dumped cleanly BEFORE the kill — the kill test
    # is about the FLIGHT stream surviving; the trace is the join input
    tracer.dump(trace_path)
    assert any(e["event"] == "failover"
               for e in flight.events_for(ctx.trace_id)), "no failover?"
    print(f"READY {ctx.trace_id}", flush=True)
    while True:  # parent SIGKILLs us here — nothing below ever runs
        time.sleep(1.0)


if __name__ == "__main__":
    main()
