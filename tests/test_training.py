"""End-to-end training tests — the analog of the reference's
``DistriOptimizerSpec``/``LocalOptimizerSpec`` (local-mode Spark in one JVM
→ here: LocalOptimizer on 1 device, DistriOptimizer on the virtual
8-device CPU mesh)."""

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch, Sample
from bigdl_tpu.dataset import image, mnist
from bigdl_tpu.models.lenet import lenet5
from bigdl_tpu.utils import checkpoint as ckpt


def mnist_pipeline(n, batch, seed=0, train_mean=None):
    imgs, labels = mnist.synthetic_mnist(n, seed=seed)
    samples = mnist.to_samples(imgs, labels)
    return (DataSet.array(samples)
            >> image.BytesToGreyImg()
            >> image.GreyImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD)
            >> SampleToMiniBatch(batch))


def small_mlp():
    return (nn.Sequential()
            .add(nn.Reshape((784,)))
            .add(nn.Linear(784, 64)).add(nn.ReLU())
            .add(nn.Linear(64, 10)).add(nn.LogSoftMax()))


class TestLocalOptimizer:
    def test_mlp_learns_synthetic_mnist(self):
        train = mnist_pipeline(512, 64)
        val = mnist_pipeline(128, 64, seed=1)
        model = small_mlp()
        opt = (optim.LocalOptimizer(model, train, nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(learning_rate=3e-3))
               .set_end_when(optim.max_epoch(8))
               .set_validation(optim.every_epoch(), val,
                               [optim.Top1Accuracy()]))
        opt.optimize()
        assert opt.state["loss"] < 0.5
        assert opt.state["score"] > 0.8  # validation top-1

    def test_lenet_one_epoch_runs(self):
        train = mnist_pipeline(128, 32)
        model = lenet5()
        opt = (optim.LocalOptimizer(model, train, nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=0.05,
                                           momentum=0.9))
               .set_end_when(optim.max_epoch(1)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])

    def test_checkpoint_and_resume(self, tmp_path):
        train = mnist_pipeline(128, 32)
        model = small_mlp()
        path = str(tmp_path / "ckpt")
        opt = (optim.LocalOptimizer(model, train, nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(1e-3))
               .set_end_when(optim.max_iteration(6))
               .set_checkpoint(path, optim.several_iteration(2)))
        opt.optimize()
        latest = ckpt.latest_checkpoint(path)
        assert latest is not None and latest.endswith("model.6")
        blob = ckpt.load_checkpoint(latest)
        assert blob["driver_state"]["neval"] == 6
        # resume: params flow back into a fresh optimizer
        model2 = small_mlp()
        model2._params = blob["params"]
        model2._state = blob["model_state"]
        opt2 = (optim.LocalOptimizer(model2, train, nn.ClassNLLCriterion())
                .set_optim_method(optim.Adam(1e-3))
                .set_state(blob["driver_state"])
                .set_end_when(optim.max_iteration(8)))
        opt2.optimize()
        assert opt2.state["neval"] == 8

    def test_min_loss_stop(self):
        train = mnist_pipeline(256, 64)
        opt = (optim.LocalOptimizer(small_mlp(), train,
                                    nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(2e-3))
               .set_end_when(optim.min_loss(1.5).or_(
                   optim.max_epoch(10))))
        opt.optimize()
        assert opt.state["loss"] <= 1.5 or opt.state["epoch"] >= 10


class TestDistriOptimizer:
    def test_dp_trains_on_8_device_mesh(self, devices):
        train = mnist_pipeline(512, 64)  # 64 = 8 per device
        model = small_mlp()
        opt = (optim.DistriOptimizer(model, train, nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(learning_rate=3e-3))
               .set_end_when(optim.max_epoch(5)))
        opt.optimize()
        assert opt.state["loss"] < 1.0

    def test_zero1_matches_replicated(self, devices):
        """Sharded-update (ZeRO-1) must be numerically equivalent to the
        replicated update — the reference's sharded AllReduceParameter is
        semantically a plain sync-SGD step."""
        train1 = mnist_pipeline(256, 32, seed=2)
        train2 = mnist_pipeline(256, 32, seed=2)
        m1, m2 = small_mlp(), small_mlp()
        common = dict(learning_rate=0.05, momentum=0.9)
        o1 = (optim.DistriOptimizer(m1, train1, nn.ClassNLLCriterion(),
                                    parameter_sharding=True)
              .set_optim_method(optim.SGD(**common))
              .set_seed(5)
              .set_end_when(optim.max_iteration(4)))
        o2 = (optim.DistriOptimizer(m2, train2, nn.ClassNLLCriterion(),
                                    parameter_sharding=False)
              .set_optim_method(optim.SGD(**common))
              .set_seed(5)
              .set_end_when(optim.max_iteration(4)))
        o1.optimize()
        o2.optimize()
        p1 = jax.tree_util.tree_leaves(m1._params)
        p2 = jax.tree_util.tree_leaves(m2._params)
        for a, b in zip(p1, p2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_retry_from_checkpoint(self, tmp_path, devices):
        """Reference failure model: crash mid-training → reload latest
        checkpoint and continue (DistriOptimizer.scala:981-1061)."""
        train = mnist_pipeline(256, 32)
        model = small_mlp()
        path = str(tmp_path / "ck")
        opt = (optim.DistriOptimizer(model, train, nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(1e-3))
               .set_end_when(optim.max_iteration(6))
               .set_checkpoint(path, optim.several_iteration(2)))
        # inject a one-shot failure at iteration 4
        real_lr = opt.optim_method.current_lr
        calls = {"n": 0}

        def flaky_lr(it, ep, metric=None):
            calls["n"] += 1
            if calls["n"] == 4:
                raise RuntimeError("injected executor failure")
            return real_lr(it, ep, metric)

        opt.optim_method.current_lr = flaky_lr
        opt.optimize()
        assert opt.state["neval"] == 6  # completed despite the crash

    def test_gradient_clipping_in_step(self, devices):
        train = mnist_pipeline(128, 32)
        opt = (optim.DistriOptimizer(small_mlp(), train,
                                     nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=10.0))  # explosive
               .set_gradient_clipping_by_l2_norm(0.5)
               .set_end_when(optim.max_iteration(5)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])


class TestSummaries:
    def test_tensorboard_event_file_written(self, tmp_path):
        from bigdl_tpu.utils.summary import TrainSummary, crc32c
        ts = TrainSummary(str(tmp_path), "app")
        ts.add_scalar("Loss", 1.25, 1)
        ts.add_scalar("Loss", 0.75, 2)
        ts.add_histogram("weights", np.random.default_rng(0).normal(0, 1, 100), 1)
        ts.close()
        files = list((tmp_path / "app" / "train").iterdir())
        assert len(files) == 1
        data = files[0].read_bytes()
        assert len(data) > 48  # version event + 3 records
        # crc32c known-answer: "123456789" -> 0xE3069283
        assert crc32c(b"123456789") == 0xE3069283


class TestReviewRegressions:
    def test_plateau_not_decayed_per_iteration(self):
        """Plateau must step once per VALIDATION, not once per iteration."""
        train = mnist_pipeline(256, 32)
        val = mnist_pipeline(64, 32, seed=1)
        sched = optim.Plateau(factor=0.1, patience=100, mode="max")
        method = optim.SGD(learning_rate=0.1, learning_rate_schedule=sched)
        opt = (optim.LocalOptimizer(small_mlp(), train,
                                    nn.ClassNLLCriterion())
               .set_optim_method(method)
               .set_end_when(optim.max_iteration(20))
               .set_validation(optim.several_iteration(5), val,
                               [optim.Top1Accuracy()]))
        opt.optimize()
        # 20 iterations but only 4 validations < patience: no decay at all
        assert sched._scale == 1.0
        assert sched._wait <= 4

    def test_empty_validation_set_raises_clear_error(self):
        train = mnist_pipeline(128, 32)
        val = mnist_pipeline(16, 32)  # 16 samples, batch 32 -> zero batches
        opt = (optim.LocalOptimizer(small_mlp(), train,
                                    nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(1e-3))
               .set_end_when(optim.max_iteration(2))
               .set_validation(optim.several_iteration(1), val,
                               [optim.Top1Accuracy()]))
        with pytest.raises(ValueError, match="drop_remainder"):
            opt.optimize()

    def test_multi_input_pytree_batch(self):
        """Tuple inputs must reach the model as a tuple, not get stacked."""
        from bigdl_tpu.dataset import LocalDataSet, MiniBatch

        class TupleBatches:
            def size(self):
                return 64

            def shuffle(self):
                pass

            def data(self, train):
                def gen():
                    rng = np.random.default_rng(0)
                    while True:
                        a = rng.normal(0, 1, (8, 4)).astype(np.float32)
                        b = rng.normal(0, 1, (8, 6)).astype(np.float32)
                        y = rng.integers(0, 2, (8,)).astype(np.int32)
                        yield MiniBatch((a, b), y)
                return gen()

        model = (nn.Sequential()
                 .add(nn.ParallelTable()
                      .add(nn.Linear(4, 8)).add(nn.Linear(6, 8)))
                 .add(nn.JoinTable(1))
                 .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
        opt = (optim.LocalOptimizer(model, TupleBatches(),
                                    nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(1e-3))
               .set_end_when(optim.max_iteration(3)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])

    def test_mid_epoch_resume_fast_forwards(self):
        train = mnist_pipeline(256, 32)
        opt = (optim.LocalOptimizer(small_mlp(), train,
                                    nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(1e-3))
               .set_state({"records_processed_this_epoch": 128})
               .set_end_when(optim.max_iteration(4)))
        opt.optimize()
        # 128 skipped + 4*32 trained = 256 -> exactly one epoch rollover
        assert opt.state["epoch"] == 1
        assert opt.state["records_processed_this_epoch"] == 0


class TestMixedPrecision:
    def test_bf16_compute_trains(self):
        train = mnist_pipeline(256, 64)
        model = small_mlp()
        opt = (optim.LocalOptimizer(model, train, nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(3e-3))
               .set_compute_dtype(jnp.bfloat16)
               .set_end_when(optim.max_epoch(6)))
        opt.optimize()
        assert opt.state["loss"] < 1.0
        # master params stay f32
        for leaf in jax.tree_util.tree_leaves(model._params):
            assert leaf.dtype == jnp.float32

    def test_bf16_grads_match_f32_direction(self):
        from bigdl_tpu.utils.precision import mixed_precision_loss_fn
        model = small_mlp()
        p, s = model.init(jax.random.PRNGKey(0))
        crit = nn.ClassNLLCriterion()
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 1, 28, 28))
        y = jnp.zeros((16,), jnp.int32)

        def f32_loss(p):
            out, _ = model.apply(p, s, x, training=True)
            return crit.apply(out, y)

        mp = mixed_precision_loss_fn(model, crit)
        g32 = jax.grad(f32_loss)(p)
        g16 = jax.grad(lambda p: mp(p, s, x, y, None)[0])(p)
        # cosine similarity of flattened grads should be ~1
        from jax.flatten_util import ravel_pytree
        a, _ = ravel_pytree(g32)
        b, _ = ravel_pytree(g16)
        assert b.dtype == jnp.float32
        cos = float(jnp.dot(a, b) / (jnp.linalg.norm(a) * jnp.linalg.norm(b)))
        assert cos > 0.99, cos
