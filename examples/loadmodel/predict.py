"""Load a pretrained model in any supported format and predict.

Mirror of the reference ``DL/example/loadmodel/`` (AlexNet +
``ModelValidator`` loading BigDL/Caffe/Torch models).  Demonstrates the
interop surface end-to-end: export a trained model to the BigDL protobuf
format and to a frozen TF GraphDef, reload both, and check the three
give identical predictions.
"""

from __future__ import annotations

import argparse
import os
import tempfile

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default=None,
                   help="path to a .bigdl model (default: train a fresh "
                        "LeNet on synthetic MNIST)")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch, image, mnist
    from bigdl_tpu.interop import (load_bigdl_module, load_tf_graph,
                                   save_bigdl_module, save_tf_graph)
    from bigdl_tpu.models.lenet import lenet5

    if args.model:
        model = load_bigdl_module(args.model)
    else:
        imgs, lbls = mnist.synthetic_mnist(1024)
        ds = (DataSet.array(mnist.to_samples(imgs, lbls))
              >> image.BytesToGreyImg()
              >> image.GreyImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD)
              >> SampleToMiniBatch(128))
        model = lenet5(class_num=10)
        (optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion())
         .set_optim_method(optim.SGD(learning_rate=0.05, momentum=0.9,
                                     dampening=0.0))
         .set_end_when(optim.max_epoch(2))).optimize()

    model.training = False
    x = np.random.RandomState(0).rand(4, 1, 28, 28).astype(np.float32)
    ref = np.argmax(np.asarray(model.forward(x)), -1)

    tmp = tempfile.mkdtemp()
    bigdl_path = os.path.join(tmp, "model.bigdl")
    save_bigdl_module(model, bigdl_path)
    m1 = load_bigdl_module(bigdl_path)
    m1.training = False
    p1 = np.argmax(np.asarray(m1.forward(x)), -1)

    tf_path = os.path.join(tmp, "model.pb")
    inp, out = save_tf_graph(model, tf_path, input_shape=(4, 1, 28, 28))
    m2 = load_tf_graph(tf_path, inputs=[inp], outputs=[out])
    p2 = np.argmax(np.asarray(m2.forward(x)), -1)

    assert (ref == p1).all() and (ref == p2).all(), (ref, p1, p2)
    print(f"predictions agree across native/bigdl/tf formats: {ref}")


if __name__ == "__main__":
    main()
