"""ModelValidator: load a model in ANY supported format and measure
Top-1/Top-5 on a validation set.

Mirror of the reference ``DL/example/loadmodel/ModelValidator.scala``
(``--modelType {bigdl,caffe,torch}`` + AlexNet/Inception validation).
Without ``--model`` it trains a small AlexNet-style net on synthetic
data, exports it to EVERY format, and validates each reload — the full
interop matrix exercised through the evaluator.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def alexnet_small(class_num: int = 10):
    """AlexNet-shaped net scaled to 32x32 inputs (the reference
    validates full AlexNet from ``example/loadmodel/AlexNet.scala``)."""
    from bigdl_tpu import nn
    return nn.Sequential(
        nn.SpatialConvolution(3, 16, 3, 3, 1, 1, 1, 1, name="conv1"),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2, ceil_mode=True),
        nn.SpatialCrossMapLRN(5, 1e-4, 0.75, name="lrn1"),
        nn.SpatialConvolution(16, 32, 3, 3, 1, 1, 1, 1, name="conv2"),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2, ceil_mode=True),
        nn.Flatten(),
        nn.Linear(32 * 8 * 8, 64, name="fc1"),
        nn.ReLU(),
        nn.Linear(64, class_num, name="fc2"),
        nn.SoftMax(),
        name="AlexNetSmall")


def main():
    p = argparse.ArgumentParser(description="Validate a saved model")
    p.add_argument("--model", default=None, help="model file to validate")
    p.add_argument("--model-type", default="bigdl",
                   choices=["bigdl", "caffe", "torch"],
                   help="format of --model (reference modelType flag)")
    p.add_argument("--prototxt", default=None,
                   help="net definition (caffe models)")
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.interop import (load_bigdl_module, load_caffe_model,
                                   load_torch_module, save_bigdl_module,
                                   save_caffe, save_torch_module)
    from bigdl_tpu.optim.predictor import Evaluator

    rng = np.random.RandomState(0)
    n, classes = 512, 10
    centers = rng.randn(classes, 3, 1, 1).astype(np.float32) * 2
    yv = rng.randint(0, classes, n)
    xv = (centers[yv] + rng.randn(n, 3, 32, 32).astype(np.float32) * 0.5)
    val_set = (DataSet.array([Sample(x, np.int32(t))
                              for x, t in zip(xv, yv)])
               >> SampleToMiniBatch(args.batch_size,
                                    drop_remainder=False))

    def validate(model, tag):
        model.evaluate()
        ev = Evaluator(model, params=model._params, state=model._state)
        r = ev.evaluate(val_set, [optim.Top1Accuracy(),
                                  optim.Top5Accuracy()])
        t1 = r["Top1Accuracy"].result
        t5 = r["Top5Accuracy"].result
        print(f"{tag}: top1={t1:.4f} top5={t5:.4f}")
        return t1

    loaders = {
        "bigdl": lambda path: load_bigdl_module(path),
        "torch": lambda path: load_torch_module(path),
        "caffe": lambda path: load_caffe_model(args.prototxt, path),
    }

    if args.model:
        t1 = validate(loaders[args.model_type](args.model),
                      args.model_type)
        print(f"final: top1={t1:.4f}")
        return

    # no model given: train briefly, export to every format, validate all
    import jax
    import jax.numpy as jnp
    model = alexnet_small(classes)
    model.initialize(0)
    crit = nn.CategoricalCrossEntropy()

    def loss_fn(params, x, y):
        out, _ = model.apply(params, model._state, x, training=False)
        return crit.apply(out, y)

    step = jax.jit(jax.value_and_grad(loss_fn))
    params = model._params
    for i in range(40):
        ix = rng.choice(n, 64, replace=False)
        l, g = step(params, jnp.asarray(xv[ix]), jnp.asarray(yv[ix]))
        params = jax.tree_util.tree_map(lambda a, b: a - 0.05 * b,
                                        params, g)
    model._params = params

    tmp = tempfile.mkdtemp(prefix="validator_")
    b_path = os.path.join(tmp, "m.bigdl")
    t_path = os.path.join(tmp, "m.t7")
    c_proto = os.path.join(tmp, "m.prototxt")
    c_path = os.path.join(tmp, "m.caffemodel")
    save_bigdl_module(model, b_path)
    save_torch_module(model, t_path)
    save_caffe(model, c_proto, c_path, input_shapes=[[1, 3, 32, 32]])
    args.prototxt = c_proto

    base = validate(model, "in-memory")
    accs = [validate(loaders["bigdl"](b_path), "bigdl"),
            validate(loaders["torch"](t_path), "torch"),
            validate(loaders["caffe"](c_path), "caffe")]
    assert all(abs(a - base) < 1e-6 for a in accs), \
        "reloaded models diverge from the trained one"
    print(f"final: top1={base:.4f} formats=bigdl,torch,caffe")


if __name__ == "__main__":
    main()
