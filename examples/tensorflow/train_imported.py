"""TensorFlow interop end-to-end: export → import → TRAIN the imported
graph.

Mirror of the reference ``DL/example/tensorflow/`` (``loadandsave`` +
``transferlearning``): a model crosses the TF GraphDef boundary in both
directions and the re-imported graph trains through the Optimizer via
``TFSession.train`` (reference ``utils/tf/Session.scala:111``).
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true")
    p.add_argument("-e", "--max-epoch", type=int, default=4)
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--queue-fed", action="store_true",
                   help="also demo training a GraphDef whose TFRecord "
                        "input pipeline is baked into the graph")
    args = p.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.interop import load_tf_graph, save_tf_graph
    from bigdl_tpu.interop.session import TFSession

    # 1) SAVE: a trained-ish model exits as a frozen GraphDef
    model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(),
                          nn.Linear(16, 3), nn.LogSoftMax())
    model.initialize(0)
    tmp = tempfile.mkdtemp(prefix="tf_example_")
    pb = os.path.join(tmp, "model.pb")
    # trainable=True: weights exported as VariableV2 (not frozen Consts)
    # so the re-imported graph can TRAIN (Session.train path)
    save_tf_graph(model, pb, input_shape=(1, 4), trainable=True)
    print(f"saved GraphDef: {pb} ({os.path.getsize(pb)} bytes)")

    # 2) LOAD: the GraphDef comes back as an executable module
    m = load_tf_graph(pb, inputs=["input"], outputs=["output"])
    x_check = np.random.RandomState(0).rand(2, 4).astype(np.float32)
    ref = np.asarray(model.forward(x_check))
    got = np.asarray(m.forward(x_check))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    print("reload parity: OK")

    # 3) TRAIN the imported graph (Session.train analog): synthetic
    # 3-class blobs
    rng = np.random.RandomState(1)
    centers = rng.randn(3, 4) * 3
    yb = rng.randint(0, 3, 512)
    xb = (centers[yb] + rng.randn(512, 4)).astype(np.float32)
    ds = (DataSet.array([Sample(x, np.int32(t)) for x, t in zip(xb, yb)])
          >> SampleToMiniBatch(args.batch_size))
    sess = TFSession(pb, inputs=["input"], outputs=["output"])
    sess.train(ds, nn.ClassNLLCriterion(),
               optim_method=optim.Adam(learning_rate=0.05),
               end_when=optim.max_epoch(args.max_epoch))
    out = np.asarray(sess.run(xb))
    acc = float((out.argmax(1) == yb).mean())
    print(f"final: train_acc={acc:.4f}")

    # 4) QUEUE-FED: a GraphDef whose input pipeline (TFRecord reader ->
    # decode -> example queue) is baked into the graph trains with NO
    # external dataset — the pipeline is detected and replayed
    # host-side (reference Session.scala:111-165)
    if args.queue_fed:
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "..", "tests"))
        from tfgraph_util import build_queue_graph
        from bigdl_tpu.dataset import tfrecord

        true_w = np.float32([1.0, -2.0, 3.0, 0.5])
        rng2 = np.random.default_rng(0)
        recs = []
        for _i in range(64):
            x = rng2.normal(0, 1, 4).astype(np.float32)
            recs.append(np.concatenate([x, [x @ true_w]]).astype(
                np.float32).tobytes())
        rec_path = os.path.join(tmp, "train.tfrecord")
        tfrecord.write_records(rec_path, recs)
        qpb = os.path.join(tmp, "queue_graph.pb")
        with open(qpb, "wb") as f:
            f.write(build_queue_graph(rec_path))
        qsess = TFSession(qpb, outputs=["loss"])  # inputs auto-detected
        losses = qsess.train(optim_method=optim.SGD(learning_rate=0.1),
                             epochs=args.max_epoch * 5)
        print(f"queue-fed: loss {losses[0]:.4f} -> {losses[-1]:.6f} "
              f"({len(losses)} steps, pipeline batch "
              f"{qsess.pipeline.batch_size})")


if __name__ == "__main__":
    main()
