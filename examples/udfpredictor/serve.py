"""Concurrent inference service demo — dynamic batching engine.

Mirror of the reference ``DL/example/udfpredictor/`` (a Spark-SQL UDF
serving text classification through a shared model).  Spark UDFs map to
concurrent caller threads sharing one
:class:`bigdl_tpu.serving.InferenceService`: the engine coalesces their
single-row requests into bucket-padded AOT-compiled dispatches, so N
callers cost ~N/max_batch_size device forwards instead of N.

Run (CPU demo):
    python examples/udfpredictor/serve.py --cpu --threads 16
"""

from __future__ import annotations

import argparse
from concurrent.futures import ThreadPoolExecutor

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=256)
    p.add_argument("--threads", type=int, default=16)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--timeout-ms", type=float, default=2.0)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.serving import InferenceService

    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                          nn.Linear(32, 4), nn.SoftMax())
    model.initialize(rng=0)

    # deploy-time AOT warmup: every power-of-two row bucket compiles
    # HERE, so no request ever pays a compile (stats prove it below)
    service = InferenceService(model, input_spec=((16,), np.float32),
                               max_batch_size=args.max_batch,
                               batch_timeout_ms=args.timeout_ms,
                               name="udfpredictor")

    rng = np.random.RandomState(0)
    requests = [rng.rand(1, 16).astype(np.float32)
                for _ in range(args.requests)]

    with ThreadPoolExecutor(max_workers=args.threads) as pool:
        results = list(pool.map(service.predict, requests))

    # deterministic model ⇒ identical request → identical answer
    again = service.predict(requests[0])
    assert np.array_equal(results[0], again)

    stats = service.stats()
    service.stop()
    probs = np.concatenate(results)
    lat = stats["latency_ms"] or {}
    print(f"served {len(results)} requests on {args.threads} threads; "
          f"mean top-prob {probs.max(-1).mean():.3f}")
    print(f"p95 latency {lat.get('p95', float('nan')):.2f} ms "
          f"(p50 {lat.get('p50', float('nan')):.2f} ms), "
          f"batch occupancy {stats['mean_batch_occupancy']:.2f}, "
          f"{stats['dispatch_count']} dispatches for "
          f"{stats['requests_completed']} rows, "
          f"{stats['compile_count']} compiles (all at warmup)")


if __name__ == "__main__":
    main()
