"""Concurrent inference service demo.

Mirror of the reference ``DL/example/udfpredictor/`` (a Spark-SQL UDF
serving text classification through a shared model).  Spark UDFs map to a
thread-safe ``PredictionService`` here: many request threads share one
jit-compiled forward.
"""

from __future__ import annotations

import argparse
from concurrent.futures import ThreadPoolExecutor

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--threads", type=int, default=8)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.optim import PredictionService

    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                          nn.Linear(32, 4), nn.SoftMax())
    model.initialize(rng=0)
    service = PredictionService(model)

    rng = np.random.RandomState(0)
    requests = [rng.rand(1, 16).astype(np.float32)
                for _ in range(args.requests)]

    with ThreadPoolExecutor(max_workers=args.threads) as pool:
        results = list(pool.map(service.predict, requests))

    # deterministic model ⇒ identical request → identical answer
    again = service.predict(requests[0])
    assert np.allclose(results[0], again)
    probs = np.concatenate(results)
    print(f"served {len(results)} requests on {args.threads} threads; "
          f"mean top-prob {probs.max(-1).mean():.3f}")


if __name__ == "__main__":
    main()
