"""VGG-16 CIFAR-10 training recipe.

Mirror of the reference ``DL/models/vgg/Train.scala``: VggForCifar10,
SGD lr 0.01 / weight-decay 5e-4 / momentum 0.9 with EpochStep(25, /2)
(the reference's "regime" schedule), normalize + flip/crop augmentation.
"""

from __future__ import annotations

import argparse

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="Train VGG on CIFAR-10")
    p.add_argument("-f", "--folder", default=None)
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("-e", "--max-epoch", type=int, default=90)
    p.add_argument("--learning-rate", type=float, default=0.01)
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--synthetic-n", type=int, default=1024)
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import (DataSet, MTSampleToMiniBatch,
                                   SampleToMiniBatch, cifar, image)
    from bigdl_tpu.models.vgg import vgg_for_cifar10

    if args.folder:
        tr_i, tr_l = cifar.load_cifar10(args.folder, train=True)
        te_i, te_l = cifar.load_cifar10(args.folder, train=False)
    else:
        tr_i, tr_l = cifar.synthetic_cifar(args.synthetic_n)
        te_i, te_l = cifar.synthetic_cifar(args.synthetic_n // 4, seed=9)

    norm = image.BGRImgNormalizer(cifar.TRAIN_MEAN, cifar.TRAIN_STD)
    # constructed ONCE: fresh per-sample instances would replay the same
    # "random" crop/flip draw for every sample (rng state lives in them)
    train_aug = (norm, image.RandomCropper(32, 32, pad=4), image.HFlip(),
                 image.ChannelOrder("CHW"))

    def augment(s):
        for t in train_aug:
            s = next(iter(t(iter([s]))))
        return s

    train_set = (DataSet.array(cifar.to_samples(tr_i, tr_l),
                               distributed=args.distributed)
                 >> MTSampleToMiniBatch(args.batch_size, augment, workers=8))
    val_set = (DataSet.array(cifar.to_samples(te_i, te_l))
               >> norm >> image.ChannelOrder("CHW")
               >> SampleToMiniBatch(args.batch_size, drop_remainder=False))

    model = vgg_for_cifar10(class_num=10)
    sgd = optim.SGD(learning_rate=args.learning_rate, momentum=0.9,
                    dampening=0.0, weight_decay=5e-4,
                    learning_rate_schedule=optim.EpochStep(25, 0.5))
    cls = optim.DistriOptimizer if args.distributed else optim.LocalOptimizer
    optimizer = (cls(model, train_set, nn.ClassNLLCriterion())
                 .set_optim_method(sgd)
                 .set_end_when(optim.max_epoch(args.max_epoch))
                 .set_validation(optim.every_epoch(), val_set,
                                 [optim.Top1Accuracy()]))
    optimizer.optimize()
    print(f"final: epoch={optimizer.state['epoch']} "
          f"loss={optimizer.state['loss']:.4f} "
          f"val_top1={optimizer.state.get('score', float('nan')):.4f}")
    return optimizer


if __name__ == "__main__":
    main()
