"""LeNet-5 on a SINGLE process via LocalOptimizer — the mirror of the
reference ``DL/example/lenetLocal/{Train,Test,Predict}.scala`` trio
(BigDL without Spark: ``bigdl.localMode=true``).

Covers the whole local loop in one script: train, checkpoint, reload,
evaluate (Top1), and predict a few samples.

Usage:
    python examples/lenetLocal/train.py [-f MNIST_DIR] [-b N] [-e N]
        [--checkpoint DIR] [--cpu]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="LeNet5 local training")
    p.add_argument("-f", "--folder", default=None)
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("-e", "--max-epoch", type=int, default=2)
    p.add_argument("--learning-rate", type=float, default=0.05)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--synthetic-n", type=int, default=2048)
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch, image, mnist
    from bigdl_tpu.interop import load_bigdl_module, save_bigdl_module
    from bigdl_tpu.models.lenet import lenet5
    from bigdl_tpu.optim.predictor import Evaluator, Predictor

    if args.folder:
        imgs, lbls = mnist.load_mnist(args.folder, train=True)
        vimgs, vlbls = mnist.load_mnist(args.folder, train=False)
    else:
        imgs, lbls = mnist.synthetic_mnist(args.synthetic_n)
        vimgs, vlbls = mnist.synthetic_mnist(512, seed=7)

    def pipeline(imgs, lbls, train):
        return (DataSet.array(mnist.to_samples(imgs, lbls))
                >> image.BytesToGreyImg()
                >> image.GreyImgNormalizer(mnist.TRAIN_MEAN,
                                           mnist.TRAIN_STD)
                >> SampleToMiniBatch(args.batch_size,
                                     drop_remainder=train))

    model = lenet5(class_num=10)
    criterion = nn.ClassNLLCriterion()
    optimizer = (optim.LocalOptimizer(model, pipeline(imgs, lbls, True),
                                      criterion)
                 .set_optim_method(optim.SGD(
                     learning_rate=args.learning_rate, momentum=0.9))
                 .set_end_when(optim.max_epoch(args.max_epoch)))
    trained = optimizer.optimize()

    # checkpoint + reload (Test.scala analog consumes the saved model)
    ckpt_dir = args.checkpoint or tempfile.mkdtemp(prefix="lenet_local_")
    path = os.path.join(ckpt_dir, "lenet.bigdl")
    save_bigdl_module(trained, path)
    reloaded = load_bigdl_module(path)
    reloaded.evaluate()

    ev = Evaluator(reloaded, params=reloaded._params,
                   state=reloaded._state)
    results = ev.evaluate(pipeline(vimgs, vlbls, False),
                          [optim.Top1Accuracy()])
    acc = results["Top1Accuracy"].result

    # Predict.scala analog: per-sample class predictions
    pred = Predictor(reloaded, params=reloaded._params,
                     state=reloaded._state, batch_size=args.batch_size)
    x = ((vimgs[:8].reshape(-1, 1, 28, 28).astype(np.float32))
         - mnist.TRAIN_MEAN) / mnist.TRAIN_STD
    classes = np.argmax(np.asarray(pred.predict(x)), axis=-1)
    print(f"predictions: {classes.tolist()} (truth {vlbls[:8].tolist()})")
    print(f"final: loss={optimizer.state['loss']:.4f} top1={acc:.4f} "
          f"ckpt={path}")


if __name__ == "__main__":
    main()
