"""PTB word-level language model (LSTM).

Mirror of the reference ``DL/example/languagemodel/{PTBModel,PTBWordLM}``:
tokenize a corpus into word ids, batch into (seq, next-word-seq) windows,
train the embed→LSTM×2→linear model (``models/rnn.ptb_model``), report
perplexity.

With ``-f`` pointing at ``ptb.train.txt`` it uses real PTB; without, a
deterministic synthetic Zipf corpus stands in so the example runs
anywhere.
"""

from __future__ import annotations

import argparse
import os
import sys

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="PTB LSTM language model")
    p.add_argument("-f", "--data", default=None,
                   help="ptb.train.txt path (default: synthetic corpus)")
    p.add_argument("-b", "--batch-size", type=int, default=20)
    p.add_argument("-e", "--max-epoch", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=20)
    p.add_argument("--vocab", type=int, default=200)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--scan-unroll", type=int, default=1,
                   help="unroll the time loop (exact math; speeds up "
                        "small-batch RNNs on TPU, see bench.py)")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.dataset.text import Dictionary
    from bigdl_tpu.models.rnn import ptb_model

    if args.data:
        words = open(args.data).read().replace("\n", " <eos> ").split()
    else:
        rng = np.random.default_rng(0)
        zipf = rng.zipf(1.4, size=40000)
        words = [f"w{min(int(z), args.vocab - 2)}" for z in zipf]

    dictionary = Dictionary([words], vocab_size=args.vocab)
    ids = np.asarray([dictionary.index(w) for w in words], np.int32)

    T = args.seq_len
    n_win = (len(ids) - 1) // T
    xs = ids[:n_win * T].reshape(n_win, T)
    ys = ids[1:n_win * T + 1].reshape(n_win, T)
    samples = [Sample(x, y) for x, y in zip(xs, ys)]
    ds = DataSet.array(samples) >> SampleToMiniBatch(args.batch_size)

    vocab = dictionary.vocab_size()
    model = ptb_model(vocab_size=vocab, embed_dim=args.hidden,
                      hidden_size=args.hidden, num_layers=args.layers,
                      scan_unroll=args.scan_unroll)
    criterion = nn.TimeDistributedCriterion(
        nn.CrossEntropyCriterion(), size_average=True)
    optimizer = (optim.LocalOptimizer(model, ds, criterion)
                 .set_optim_method(optim.Adam(learning_rate=0.01))
                 # LSTM steps are 3-5 ms — host dispatch is the measured
                 # bottleneck; K=8 is the production default for this
                 # workload class (bench.PRODUCTION_K, round-6 ablation)
                 .set_steps_per_dispatch(8)
                 .set_end_when(optim.max_epoch(args.max_epoch)))
    optimizer.optimize()
    loss = optimizer.state["loss"]
    ppl = float(np.exp(min(loss, 20.0)))
    print(f"final: loss={loss:.4f} perplexity={ppl:.1f} vocab={vocab}")


if __name__ == "__main__":
    main()
