"""RNN language-model training recipe.

Mirror of the reference ``DL/models/rnn/Train.scala`` (simple RNN on a
tokenized corpus via Dictionary/TextToLabeledSentence) and
``DL/example/languagemodel/PTBWordLM.scala`` (PTB LSTM with
TimeDistributedCriterion).  Feeds PTB files when ``-f`` points at
``ptb.train.txt``/``ptb.valid.txt``; otherwise a synthetic Zipf corpus.
"""

from __future__ import annotations

import argparse
import os

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="Train an RNN LM")
    p.add_argument("-f", "--folder", default=None,
                   help="dir with ptb.train.txt / ptb.valid.txt")
    p.add_argument("--model", choices=["ptb", "simple"], default="ptb")
    p.add_argument("-b", "--batch-size", type=int, default=20)
    p.add_argument("-e", "--max-epoch", type=int, default=4)
    p.add_argument("--num-steps", type=int, default=20)
    p.add_argument("--vocab-size", type=int, default=10000)
    p.add_argument("--hidden-size", type=int, default=200)
    p.add_argument("--learning-rate", type=float, default=0.005)
    p.add_argument("--scan-unroll", type=int, default=1,
                   help="unroll the time loop (exact math; speeds up "
                        "small-batch RNNs on TPU, see bench.py)")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch, text
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.rnn import ptb_model, simple_rnn

    if args.folder:
        words = text.read_ptb_words(os.path.join(args.folder,
                                                 "ptb.train.txt"))
        sents = [words]
    else:
        corpus = text.synthetic_corpus(400)
        sents = [text.sentence_tokenizer(s) for s in corpus]
        words = [w for s in sents for w in s]

    d = text.Dictionary([words], vocab_size=args.vocab_size)
    ids = d.encode(words)
    x, y = text.ptb_batches(ids, args.num_steps)
    samples = [Sample(x[i], y[i]) for i in range(len(x))]
    train_set = (DataSet.array(samples)
                 >> SampleToMiniBatch(args.batch_size))

    vocab = d.vocab_size()
    if args.model == "ptb":
        model = ptb_model(vocab_size=vocab, embed_dim=args.hidden_size,
                          hidden_size=args.hidden_size,
                          scan_unroll=args.scan_unroll)
    else:
        model = simple_rnn(input_size=vocab, hidden_size=args.hidden_size,
                           output_size=vocab,
                           scan_unroll=args.scan_unroll)

    # models end in LogSoftMax -> NLL per step (reference PTBWordLM pairs
    # TimeDistributedCriterion with CrossEntropy on raw outputs instead)
    criterion = nn.TimeDistributedCriterion(
        nn.ClassNLLCriterion(), size_average=True)
    optimizer = (optim.LocalOptimizer(model, train_set, criterion)
                 .set_optim_method(optim.Adam(
                     learning_rate=args.learning_rate))
                 .set_end_when(optim.max_epoch(args.max_epoch)))
    optimizer.optimize()
    ppl = float(np.exp(min(optimizer.state["loss"], 20.0)))
    print(f"final: epoch={optimizer.state['epoch']} "
          f"loss={optimizer.state['loss']:.4f} train_ppl={ppl:.1f}")
    return optimizer


if __name__ == "__main__":
    main()
