"""ML-pipeline-style estimator demos.

Mirror of the reference ``DL/example/MLPipeline/``:
``DLClassifierLogisticRegression`` (2-feature LR via the fit/transform
facade), ``DLClassifierLeNet`` (image classifier through the same
interface), and ``DLEstimatorMultiLabelLR`` (multi-label regression via
the raw NNEstimator).  The DataFrame is replaced by plain arrays — the
estimator facade is the ``DLEstimator``/``DLClassifier`` analog
(SURVEY §2.7).
"""

from __future__ import annotations

import argparse
import os
import sys

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true")
    p.add_argument("-e", "--max-epoch", type=int, default=20)
    args = p.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from bigdl_tpu import nn, optim
    from bigdl_tpu.estimator import NNClassifier, NNEstimator
    from bigdl_tpu.dataset import mnist
    from bigdl_tpu.models.lenet import lenet5

    rng = np.random.RandomState(0)

    # 1) DLClassifierLogisticRegression: y = 1[x0 + x1 > 1]
    x = rng.rand(512, 2).astype(np.float32)
    y = (x.sum(1) > 1.0).astype(np.int32)
    lr_model = nn.Sequential(nn.Linear(2, 2), nn.LogSoftMax())
    clf = NNClassifier(lr_model, batch_size=32, max_epoch=args.max_epoch,
                       optim_method=optim.SGD(learning_rate=0.5))
    lr_acc = (clf.fit(x, y).transform(x) == y).mean()
    print(f"logistic regression train acc: {lr_acc:.4f}")

    # 2) DLClassifierLeNet: the image classifier through fit/transform
    imgs, lbls = mnist.synthetic_mnist(4096)
    xi = ((imgs.reshape(-1, 1, 28, 28).astype(np.float32))
          - mnist.TRAIN_MEAN) / mnist.TRAIN_STD
    lenet_clf = NNClassifier(
        lenet5(class_num=10), batch_size=128, max_epoch=3,
        optim_method=optim.SGD(learning_rate=0.1, momentum=0.9))
    lenet_acc = (lenet_clf.fit(xi, lbls).transform(xi) == lbls).mean()
    print(f"lenet train acc: {lenet_acc:.4f}")

    # 3) DLEstimatorMultiLabelLR: 2-output linear regression on MSE
    xm = rng.rand(256, 2).astype(np.float32)
    w = np.asarray([[2.0, -1.0], [0.5, 1.5]], np.float32)
    ym = xm @ w.T + np.asarray([0.1, -0.2], np.float32)
    est = NNEstimator(nn.Linear(2, 2), nn.MSECriterion(), batch_size=32,
                      max_epoch=args.max_epoch,
                      optim_method=optim.Adam(learning_rate=0.05))
    fitted = est.fit(xm, ym)
    mse = float(((fitted.transform(xm) - ym) ** 2).mean())
    print(f"multi-label LR mse: {mse:.5f}")
    print(f"final: train_acc={lr_acc:.4f} lenet_acc={lenet_acc:.4f} "
          f"mse={mse:.5f}")


if __name__ == "__main__":
    main()
