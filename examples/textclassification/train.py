"""Text classification with a 1-D CNN over word embeddings.

Mirror of the reference ``DL/example/textclassification/`` (GloVe + news20
→ TemporalConvolution stack).  Without the news20/GloVe downloads it runs
on a deterministic synthetic two-topic corpus; embeddings are learned
(LookupTable) instead of pretrained.
"""

from __future__ import annotations

import argparse

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def synthetic_corpus(n=400, seed=0):
    """Two topics with disjoint preferred vocabularies."""
    import numpy as np
    rng = np.random.default_rng(seed)
    topics = [[f"alpha{i}" for i in range(20)],
              [f"beta{i}" for i in range(20)]]
    shared = [f"w{i}" for i in range(20)]
    texts, labels = [], []
    for _ in range(n):
        y = int(rng.integers(0, 2))
        words = rng.choice(topics[y] + shared, size=12)
        texts.append(" ".join(words))
        labels.append(y)
    return texts, labels


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("-e", "--max-epoch", type=int, default=6)
    p.add_argument("--seq-len", type=int, default=12)
    p.add_argument("--embed-dim", type=int, default=32)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch, text
    from bigdl_tpu.dataset.sample import Sample

    texts, labels = synthetic_corpus()
    toks = [text.sentence_tokenizer(t) for t in texts]
    d = text.Dictionary(toks)
    samples = []
    for t, y in zip(toks, labels):
        ids = d.encode(t)[: args.seq_len]
        if len(ids) < args.seq_len:
            ids = np.pad(ids, (0, args.seq_len - len(ids)))
        samples.append(Sample(ids.astype(np.int32), np.int32(y)))

    # embed → temporal conv → max-over-time → classify (the reference's
    # CNN text classifier shape)
    model = (nn.Sequential(name="TextCNN")
             .add(nn.LookupTable(d.vocab_size(), args.embed_dim))
             .add(nn.TemporalConvolution(args.embed_dim, 64, 3))
             .add(nn.ReLU())
             .add(nn.Lambda(lambda x: x.max(axis=1)))
             .add(nn.Linear(64, 2))
             .add(nn.LogSoftMax()))

    train_set = DataSet.array(samples) >> SampleToMiniBatch(args.batch_size)
    opt = (optim.LocalOptimizer(model, train_set, nn.ClassNLLCriterion())
           .set_optim_method(optim.Adam(learning_rate=0.01))
           .set_end_when(optim.max_epoch(args.max_epoch)))
    opt.optimize()

    model.training = False
    xs = np.stack([s.feature for s in samples])
    ys = np.asarray(labels)
    acc = (np.argmax(np.asarray(model.forward(xs)), -1) == ys).mean()
    print(f"final: loss={opt.state['loss']:.4f} train_acc={acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
