"""Image transfer learning: frozen pretrained trunk + trainable head.

Mirror of the reference ``DL/example/dlframes/imageTransferLearning``
(and ``imageInference``): a pretrained conv trunk extracts features
(inference only), a small classifier head trains on top via the
estimator facade — the DataFrame pipeline replaced by plain arrays.
"""

from __future__ import annotations

import argparse
import os
import sys

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true")
    p.add_argument("-e", "--max-epoch", type=int, default=10)
    p.add_argument("-n", "--samples", type=int, default=512)
    args = p.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from bigdl_tpu import nn, optim
    from bigdl_tpu.estimator import NNClassifier
    from bigdl_tpu.optim.predictor import Predictor

    rng = np.random.RandomState(0)

    # "pretrained" trunk (stands in for a loaded zoo model; swap with
    # interop.load_bigdl_module / load_caffe_model for real weights)
    trunk = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialConvolution(8, 16, 3, 3, 1, 1, 1, 1),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Flatten())
    trunk.initialize(7)
    trunk.evaluate()

    # 2-class dataset the trunk was NOT trained on
    n = args.samples
    y = rng.randint(0, 2, n)
    x = rng.rand(n, 3, 16, 16).astype(np.float32)
    x[y == 1, :, 4:12, 4:12] += 0.8  # class-1 images get a bright square

    # inference pass: frozen trunk extracts features (imageInference)
    feats = np.asarray(Predictor(trunk, params=trunk._params,
                                 state=trunk._state,
                                 batch_size=128).predict(x))
    print(f"trunk features: {feats.shape}")

    # trainable head fits on the features (imageTransferLearning)
    head = nn.Sequential(nn.Linear(feats.shape[1], 16), nn.ReLU(),
                         nn.Linear(16, 2), nn.LogSoftMax())
    clf = NNClassifier(head, batch_size=64, max_epoch=args.max_epoch,
                       optim_method=optim.Adam(learning_rate=0.01))
    fitted = clf.fit(feats, y)
    acc = float((fitted.transform(feats) == y).mean())
    print(f"final: train_acc={acc:.4f}")


if __name__ == "__main__":
    main()
