"""Inception-v1 ImageNet training recipe.

Mirror of the reference ``DL/models/inception/Train.scala`` +
``Options.scala``: Inception-v1, SGD momentum 0.9 / weight-decay 1e-4,
poly(0.5) LR decay over ``max_iteration`` (the reference's default
recipe), warmup supported via ``--warmup-epochs`` (Warmup →
SequentialSchedule, as the distributed recipe uses), Inception-style
random-alter-aspect crop + flip augmentation.

Without a real ImageNet tree it trains on a synthetic 224x224 dataset so
the script runs anywhere (the reference needs its seq-file pipeline).
"""

from __future__ import annotations

import argparse

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def synthetic_imagenet(n, size=224, classes=1000, seed=0):
    import numpy as np
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, n).astype(np.int32)
    imgs = rng.integers(0, 60, (n, size, size, 3)).astype(np.float32)
    for i, y in enumerate(labels):
        r, c = divmod(int(y) % 16, 4)
        imgs[i, r * 56:(r + 1) * 56, c * 56:(c + 1) * 56, int(y) % 3] += 150
    return imgs, labels


def main():
    p = argparse.ArgumentParser(description="Train Inception-v1")
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--max-iteration", type=int, default=62000)
    p.add_argument("-e", "--max-epoch", type=int, default=None)
    p.add_argument("--learning-rate", type=float, default=0.0898)
    p.add_argument("--warmup-epochs", type=int, default=0)
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--synthetic-n", type=int, default=256)
    p.add_argument("--image-size", type=int, default=224)
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, MTSampleToMiniBatch, cifar
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.inception import inception_v1
    from bigdl_tpu.transform import vision as V

    imgs, labels = synthetic_imagenet(args.synthetic_n, args.image_size,
                                      args.classes)
    samples = cifar.to_samples(imgs.astype("uint8"), labels)

    aug = (V.RandomAlterAspect(target_size=args.image_size)
           >> V.HFlip()
           >> V.ChannelNormalize((123.0, 117.0, 104.0), (58.4, 57.1, 57.4))
           >> V.ImageFrameToSample())

    def augment(s):
        f = V.ImageFeature(s.feature, s.label)
        return aug(f)["sample"]

    train_set = (DataSet.array(samples, distributed=args.distributed)
                 >> MTSampleToMiniBatch(args.batch_size, augment, workers=8))

    schedule = optim.Poly(0.5, args.max_iteration)
    if args.warmup_epochs:
        iters_per_epoch = max(1, len(samples) // args.batch_size)
        warm = args.warmup_epochs * iters_per_epoch
        delta = args.learning_rate / max(warm, 1)
        seq = optim.SequentialSchedule()
        seq.add(optim.Warmup(delta, warm), warm)
        seq.add(optim.Poly(0.5, args.max_iteration))
        schedule = seq
    sgd = optim.SGD(learning_rate=args.learning_rate, momentum=0.9,
                    dampening=0.0, weight_decay=1e-4,
                    learning_rate_schedule=schedule)

    end = (optim.max_epoch(args.max_epoch) if args.max_epoch
           else optim.max_iteration(args.max_iteration))
    model = inception_v1(class_num=args.classes)
    cls = optim.DistriOptimizer if args.distributed else optim.LocalOptimizer
    optimizer = (cls(model, train_set, nn.ClassNLLCriterion())
                 .set_optim_method(sgd)
                 .set_end_when(end))
    optimizer.optimize()
    print(f"final: epoch={optimizer.state['epoch']} "
          f"loss={optimizer.state['loss']:.4f}")
    return optimizer


if __name__ == "__main__":
    main()
