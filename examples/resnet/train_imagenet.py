"""ResNet-50 ImageNet training recipe.

Mirror of the reference ``DL/models/resnet/TrainImageNet.scala`` +
``README.md:131-149`` large-batch recipe: batch 8192, 90 epochs, 5-epoch
linear warmup to maxLr 3.2, then /10 at epochs 30/60/80, SGD momentum 0.9
weight-decay 1e-4, label-smoothing-free NLL.  Input pipeline:
random-alter-aspect crop + flip + channel normalization (the reference's
seq-file ImageNet path; Hadoop SequenceFiles via ``--seqfiles`` glob or a
synthetic stand-in anywhere).
"""

from __future__ import annotations

import argparse
import glob as globmod

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="Train ResNet-50 on ImageNet")
    p.add_argument("--seqfiles", default=None,
                   help="glob of Hadoop SequenceFiles holding raw "
                        "HWC uint8 images (reference seq-file pipeline)")
    p.add_argument("-b", "--batch-size", type=int, default=256,
                   help="global batch (reference recipe: 8192 across "
                        "the cluster)")
    p.add_argument("-e", "--max-epoch", type=int, default=90)
    p.add_argument("--max-lr", type=float, default=3.2,
                   help="post-warmup LR for the batch-8192 recipe; "
                        "scale linearly with batch")
    p.add_argument("--warmup-epochs", type=int, default=5)
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--depth", type=int, default=50, choices=[50])
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--nhwc", action="store_true",
                   help="TPU-preferred layout")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--synthetic-n", type=int, default=512)
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import (DataSet, MTSampleToMiniBatch, seqfile)
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.resnet import resnet50
    from bigdl_tpu.transform import vision as V

    size = args.image_size
    # Samples hold uint8 HWC images; augmentation converts to float per
    # batch.  Keeping the set in host memory mirrors the reference's
    # CachedDistriDataSet (the whole dataset cached across cluster RAM,
    # divided per host by DistributedDataSet sharding).
    samples = []
    if args.seqfiles:
        paths = sorted(globmod.glob(args.seqfiles))
        for label, blob in seqfile.seqfiles_to_byte_records(paths):
            img = np.frombuffer(blob, np.uint8)
            side = int(round((img.size / 3) ** 0.5))
            if side * side * 3 != img.size:
                raise ValueError(
                    f"seqfile record of {img.size} bytes is not a square "
                    "raw-HWC image; pre-resize to a fixed square (the "
                    "raw format carries no dimension header)")
            # reference seqfile labels are 1-based (Torch convention);
            # this framework's criterions are 0-based
            samples.append(Sample(img.reshape(side, side, 3),
                                  np.int32(label - 1)))
    else:
        rng = np.random.default_rng(0)
        labels = rng.integers(0, args.classes, args.synthetic_n)
        for y in labels:
            img = rng.integers(0, 60, (size, size, 3)).astype(np.uint8)
            r, c = divmod(int(y) % 16, 4)
            img[r * (size // 4):(r + 1) * (size // 4),
                c * (size // 4):(c + 1) * (size // 4), int(y) % 3] += 150
            samples.append(Sample(img, np.int32(y)))

    fmt = "NHWC" if args.nhwc else "NCHW"
    aug = (V.RandomAlterAspect(target_size=size)
           >> V.HFlip()
           >> V.ChannelNormalize((123.68, 116.78, 103.94),
                                 (58.4, 57.1, 57.4))
           >> V.ImageFrameToSample(to_chw=(fmt == "NCHW")))

    def augment(s):
        # ImageFeature casts to float32 itself; no extra copy here
        f = V.ImageFeature(s.feature, s.label)
        return aug(f)["sample"]

    train_set = (DataSet.array(samples, distributed=args.distributed)
                 >> MTSampleToMiniBatch(args.batch_size, augment,
                                        workers=8))

    iters_per_epoch = max(1, len(samples) // args.batch_size)
    warm = args.warmup_epochs * iters_per_epoch
    # linear warmup to max_lr, then /10 at epochs 30/60/80 — exactly the
    # reference recipe's EpochDecayWithWarmUp (README.md:131-149)
    base_lr = args.max_lr / max(warm, 1)
    delta = (args.max_lr - base_lr) / max(warm, 1)

    def decay(epoch):
        return sum(1 for e in (30, 60, 80) if epoch >= e)

    sgd = optim.SGD(learning_rate=base_lr, momentum=0.9, dampening=0.0,
                    weight_decay=1e-4,
                    learning_rate_schedule=optim.EpochDecayWithWarmUp(
                        warm, delta, decay))

    model = resnet50(class_num=args.classes, format=fmt)
    cls = optim.DistriOptimizer if args.distributed else optim.LocalOptimizer
    optimizer = (cls(model, train_set, nn.ClassNLLCriterion())
                 .set_optim_method(sgd)
                 .set_end_when(optim.max_epoch(args.max_epoch)))
    optimizer.optimize()
    print(f"final: epoch={optimizer.state['epoch']} "
          f"loss={optimizer.state['loss']:.4f}")
    return optimizer


if __name__ == "__main__":
    main()
