"""ResNet CIFAR-10 training recipe.

Mirror of the reference ``DL/models/resnet/TrainCIFAR10.scala``: ResNet-20
(6n+2), SGD momentum 0.9 / weight-decay 1e-4 / nesterov, LR 0.1 with the
multistep /10 at epochs 80 and 120 (165 epochs total), pad-4 random crop
32x32 + horizontal flip + per-channel normalization augmentation.

Runs on real CIFAR-10 (``-f`` pointing at cifar-10-batches-{bin,py}) or a
deterministic synthetic stand-in so the script works anywhere.
"""

from __future__ import annotations

import argparse

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="Train ResNet on CIFAR-10")
    p.add_argument("-f", "--folder", default=None,
                   help="CIFAR-10 dir (default: synthetic data)")
    p.add_argument("--depth", type=int, default=20)
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("-e", "--max-epoch", type=int, default=165)
    p.add_argument("--learning-rate", type=float, default=0.1)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--synthetic-n", type=int, default=2048)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--summary", default=None)
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import logging
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import (DataSet, MTSampleToMiniBatch,
                                   SampleToMiniBatch, cifar, image)
    from bigdl_tpu.models.resnet import resnet_cifar

    if args.folder:
        tr_i, tr_l = cifar.load_cifar10(args.folder, train=True)
        te_i, te_l = cifar.load_cifar10(args.folder, train=False)
    else:
        tr_i, tr_l = cifar.synthetic_cifar(args.synthetic_n)
        te_i, te_l = cifar.synthetic_cifar(args.synthetic_n // 4, seed=9)

    norm = image.BGRImgNormalizer(cifar.TRAIN_MEAN, cifar.TRAIN_STD)
    # constructed ONCE: the transforms carry (thread-safe) rng state, so a
    # fresh instance per sample would replay the same "random" draw forever
    train_aug = (norm, image.RandomCropper(32, 32, pad=4), image.HFlip(),
                 image.ChannelOrder("CHW"))

    def augment(s):
        # reference recipe: pad 4 + random crop 32 + random hflip (train)
        for t in train_aug:
            s = next(iter(t(iter([s]))))
        return s

    train_set = (DataSet.array(cifar.to_samples(tr_i, tr_l),
                               distributed=args.distributed)
                 >> MTSampleToMiniBatch(args.batch_size, augment, workers=8))
    val_set = (DataSet.array(cifar.to_samples(te_i, te_l))
               >> image.BGRImgNormalizer(cifar.TRAIN_MEAN, cifar.TRAIN_STD)
               >> image.ChannelOrder("CHW")
               >> SampleToMiniBatch(args.batch_size, drop_remainder=False))

    model = resnet_cifar(depth=args.depth, class_num=10)
    sgd = optim.SGD(
        learning_rate=args.learning_rate, momentum=0.9, dampening=0.0,
        nesterov=True, weight_decay=args.weight_decay,
        learning_rate_schedule=optim.MultiStep([80, 120], 0.1,
                                               epoch_based=True))
    cls = optim.DistriOptimizer if args.distributed else optim.LocalOptimizer
    optimizer = (cls(model, train_set, nn.ClassNLLCriterion())
                 .set_optim_method(sgd)
                 .set_end_when(optim.max_epoch(args.max_epoch))
                 .set_validation(optim.every_epoch(), val_set,
                                 [optim.Top1Accuracy()]))
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, optim.every_epoch())
    if args.summary:
        from bigdl_tpu.utils.summary import TrainSummary, ValidationSummary
        optimizer.set_train_summary(TrainSummary(args.summary, "resnet"))
        optimizer.set_val_summary(ValidationSummary(args.summary, "resnet"))
    optimizer.optimize()
    print(f"final: epoch={optimizer.state['epoch']} "
          f"loss={optimizer.state['loss']:.4f} "
          f"val_top1={optimizer.state.get('score', float('nan')):.4f}")
    return optimizer


if __name__ == "__main__":
    main()
