"""Constituency TreeLSTM sentiment classification.

Mirror of the reference ``DL/example/treeLSTMSentiment/`` (BinaryTreeLSTM
on SST parse trees).  Runs on synthetic right-leaning parse trees whose
sentiment is determined by the leaf vocabulary, so the tree composition
has real signal to learn.
"""

from __future__ import annotations

import argparse

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def synthetic_trees(n=256, n_leaves=6, vocab=40, seed=0):
    """Right-leaning binary trees; label = majority leaf polarity."""
    import numpy as np
    rng = np.random.default_rng(seed)
    n_nodes = 2 * n_leaves - 1
    # node rows [left, right, leaf_ix], 1-based, children before parents
    tree = np.zeros((n_nodes, 3), np.float32)
    for i in range(n_leaves):
        tree[i] = [0, 0, i + 1]
    nxt = n_leaves
    prev = n_leaves  # node id of rightmost leaf (1-based)
    # compose leaves right-to-left: (l5,(l4,(l3,...)))
    for k in range(n_leaves - 1):
        li = n_leaves - 1 - k  # leaf id to the left
        tree[nxt] = [li, prev, 0]
        prev = nxt + 1
        nxt += 1
    tokens = rng.integers(0, vocab, (n, n_leaves))
    labels = (np.where(tokens < vocab // 2, 1, -1).sum(1) > 0).astype(
        np.int32)
    return tokens, np.tile(tree[None], (n, 1, 1)), labels, n_nodes


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--max-epoch", type=int, default=60)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--embed-dim", type=int, default=16)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu import nn, optim

    vocab = 40
    tokens, trees, labels, n_nodes = synthetic_trees(vocab=vocab)
    embed = nn.LookupTable(vocab, args.embed_dim)
    tree_lstm = nn.BinaryTreeLSTM(args.embed_dim, args.hidden)
    head = nn.Linear(args.hidden, 2)

    ek, tk, hk = jax.random.split(jax.random.PRNGKey(0), 3)
    e_p, _ = embed.init(ek)
    t_p, _ = tree_lstm.init(tk)
    h_p, _ = head.init(hk)
    params = {"embed": e_p, "tree": t_p, "head": h_p}

    xs = jnp.asarray(tokens)
    ts = jnp.asarray(trees)
    ys = jnp.asarray(labels)

    def loss_fn(p):
        emb, _ = embed.apply(p["embed"], {}, xs)
        states, _ = tree_lstm.apply(p["tree"], {}, (emb, ts))
        root = states[:, -1]  # root is the last (topologically) node
        logits, _ = head.apply(p["head"], {}, root)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, ys[:, None], 1))

    step = jax.jit(jax.value_and_grad(loss_fn))
    method = optim.Adam(learning_rate=0.02)
    opt_state = method.init_state(params)
    update = jax.jit(method.update)  # one wrapper: compile once
    for i in range(args.max_epoch):
        loss, g = step(params)
        params, opt_state = update(g, params, opt_state, 0.02, i)
    emb, _ = embed.apply(params["embed"], {}, xs)
    states, _ = tree_lstm.apply(params["tree"], {}, (emb, ts))
    logits, _ = head.apply(params["head"], {}, states[:, -1])
    acc = float((jnp.argmax(logits, -1) == ys).mean())
    print(f"final: loss={float(loss):.4f} train_acc={acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
