"""Image-folder classification through the vision-2.0 pipeline.

Mirror of the reference ``DL/example/imageclassification/ImagePredictor``
(+ ``MlUtils``): read images, run the ImageFrame feature pipeline
(resize → center crop → channel normalize), batch, and predict with a
classifier — the inference-side twin of the Inception training recipe.

With ``--folder`` pointing at JPEG/PNG files it classifies those;
without, it generates a synthetic image set so the example runs
anywhere.
"""

from __future__ import annotations

import argparse
import os
import sys

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="Classify an image folder")
    p.add_argument("--folder", default=None,
                   help="dir of images (default: synthetic)")
    p.add_argument("--model", default=None,
                   help=".bigdl classifier (default: fresh Inception-v1 "
                        "head on 8 classes)")
    p.add_argument("--classes", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--topn", type=int, default=3)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from bigdl_tpu.transform.vision import (AspectScale, CenterCrop,
                                            ChannelNormalize, ImageFeature,
                                            ImageFrameToSample,
                                            LocalImageFrame, MatToFloats)
    from bigdl_tpu.optim.predictor import Predictor
    from bigdl_tpu.interop import load_bigdl_module
    from bigdl_tpu.models.inception import inception_v1

    rng = np.random.default_rng(0)
    if args.folder:
        from PIL import Image
        names, mats = [], []
        for fn in sorted(os.listdir(args.folder)):
            if fn.lower().endswith((".jpg", ".jpeg", ".png")):
                img = Image.open(os.path.join(args.folder, fn))
                mats.append(np.asarray(img.convert("RGB"), np.float32))
                names.append(fn)
    else:
        names = [f"synthetic_{i}.jpg" for i in range(16)]
        mats = [rng.integers(0, 255, (280, 320, 3)).astype(np.float32)
                for _ in names]

    frame = LocalImageFrame([ImageFeature(image=m, uri=n)
                             for m, n in zip(mats, names)])
    frame = (frame
             >> AspectScale(256)
             >> CenterCrop(224, 224)
             >> ChannelNormalize((123.0, 117.0, 104.0),
                                 (58.4, 57.1, 57.4))
             >> MatToFloats()
             >> ImageFrameToSample(to_chw=True))
    batch = np.stack([f["sample"].feature for f in frame.features])

    if args.model:
        model = load_bigdl_module(args.model)
    else:
        model = inception_v1(class_num=args.classes)
        model.initialize(0)
    model.evaluate()
    pred = Predictor(model, params=model._params, state=model._state,
                     batch_size=args.batch_size)
    probs = np.exp(np.asarray(pred.predict(batch)))  # model ends LogSoftMax
    top = np.argsort(-probs, axis=1)[:, :args.topn]
    for n, t, pr in zip(names, top, probs):
        pairs = ", ".join(f"cls{c}:{pr[c]:.3f}" for c in t)
        print(f"{n}: {pairs}")
    print(f"final: predicted={len(names)} classes={probs.shape[1]}")


if __name__ == "__main__":
    main()
