"""LeNet-5 via the Estimator facade (reference ``DL/dlframes/DLClassifier``)."""
try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os, sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from bigdl_tpu import optim
from bigdl_tpu.dataset import mnist
from bigdl_tpu.estimator import NNClassifier
from bigdl_tpu.models.lenet import lenet5

x, y = mnist.synthetic_mnist(4096)
x = ((x.reshape(-1, 1, 28, 28).astype("float32"))
     - mnist.TRAIN_MEAN) / mnist.TRAIN_STD
clf = NNClassifier(lenet5(class_num=10), batch_size=128, max_epoch=3,
                   optim_method=optim.SGD(learning_rate=0.05, momentum=0.9))
fitted = clf.fit(x, y)
acc = (fitted.transform(x) == y).mean()
print(f"train accuracy: {acc:.4f}")
