"""LeNet-5 in the Keras-style API (reference ``DL/example/keras/``)."""
try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os, sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from bigdl_tpu import optim
from bigdl_tpu.dataset import mnist
from bigdl_tpu.keras import (Convolution2D, Dense, Flatten, MaxPooling2D,
                             Sequential)

x, y = mnist.synthetic_mnist(4096)
x = ((x.reshape(-1, 1, 28, 28).astype("float32"))
     - mnist.TRAIN_MEAN) / mnist.TRAIN_STD
model = Sequential([
    Convolution2D(6, 5, 5, activation="tanh", input_shape=(1, 28, 28)),
    MaxPooling2D(), Convolution2D(12, 5, 5, activation="tanh"),
    MaxPooling2D(), Flatten(), Dense(100, activation="tanh"),
    Dense(10, activation="softmax")])
model.compile(optim.SGD(learning_rate=0.05, momentum=0.9),
              "categorical_crossentropy", metrics=["accuracy"])
model.fit(x, y, batch_size=128, nb_epoch=3, validation_data=(x, y))
print("val:", model.evaluate(x, y))
