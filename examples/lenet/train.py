"""LeNet-5 training example — the TPU-native mirror of the reference's
``DL/models/lenet/Train.scala:35-101`` (the canonical BigDL entry script).

Usage:
    python examples/lenet/train.py [-f MNIST_DIR] [-b BATCH] [-e EPOCHS]
        [--distributed] [--checkpoint DIR] [--summary DIR] [--cpu]

Without ``-f`` (no MNIST idx files), trains on the deterministic synthetic
MNIST-shaped dataset so the example runs anywhere.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

try:
    import bigdl_tpu  # noqa: F401  (installed via `pip install -e .`)
except ImportError:  # running straight from a repo checkout
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="Train LeNet5 on MNIST")
    p.add_argument("-f", "--folder", default=None,
                   help="MNIST idx files dir (default: synthetic data)")
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("-e", "--max-epoch", type=int, default=5)
    p.add_argument("--learning-rate", type=float, default=0.05)
    p.add_argument("--learning-rate-decay", type=float, default=0.0)
    p.add_argument("--distributed", action="store_true",
                   help="use DistriOptimizer over the device mesh")
    p.add_argument("--checkpoint", default=None, help="checkpoint dir")
    p.add_argument("--summary", default=None, help="tensorboard log dir")
    p.add_argument("--cpu", action="store_true", help="force CPU platform")
    p.add_argument("--synthetic-n", type=int, default=4096)
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset import image, mnist
    from bigdl_tpu.models.lenet import lenet5
    from bigdl_tpu.utils.summary import TrainSummary, ValidationSummary

    if args.folder:
        train_imgs, train_lbls = mnist.load_mnist(args.folder, train=True)
        val_imgs, val_lbls = mnist.load_mnist(args.folder, train=False)
    else:
        train_imgs, train_lbls = mnist.synthetic_mnist(args.synthetic_n)
        val_imgs, val_lbls = mnist.synthetic_mnist(
            args.synthetic_n // 4, seed=99)

    def pipeline(imgs, lbls, mean, std, train=True):
        # validation keeps the ragged final batch (drop_remainder=False)
        # so every sample is scored
        return (DataSet.array(mnist.to_samples(imgs, lbls))
                >> image.BytesToGreyImg()
                >> image.GreyImgNormalizer(mean, std)
                >> SampleToMiniBatch(args.batch_size,
                                     drop_remainder=train))

    train_set = pipeline(train_imgs, train_lbls,
                         mnist.TRAIN_MEAN, mnist.TRAIN_STD)
    val_set = pipeline(val_imgs, val_lbls, mnist.TEST_MEAN, mnist.TEST_STD,
                       train=False)

    model = lenet5(class_num=10)
    cls = optim.DistriOptimizer if args.distributed else optim.LocalOptimizer
    optimizer = (cls(model, train_set, nn.ClassNLLCriterion())
                 .set_optim_method(optim.SGD(
                     learning_rate=args.learning_rate,
                     learning_rate_decay=args.learning_rate_decay,
                     momentum=0.9))
                 .set_end_when(optim.max_epoch(args.max_epoch))
                 .set_validation(optim.every_epoch(), val_set,
                                 [optim.Top1Accuracy(),
                                  optim.Top5Accuracy()]))
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, optim.every_epoch())
    if args.summary:
        optimizer.set_train_summary(TrainSummary(args.summary, "lenet"))
        optimizer.set_val_summary(ValidationSummary(args.summary, "lenet"))

    optimizer.optimize()
    print(f"final: epoch={optimizer.state['epoch']} "
          f"loss={optimizer.state['loss']:.4f} "
          f"val_top1={optimizer.state.get('score', float('nan')):.4f}")


if __name__ == "__main__":
    main()
