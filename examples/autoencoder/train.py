"""MNIST autoencoder training recipe.

Mirror of the reference ``DL/models/autoencoder/Train.scala``: 784→32→784
sigmoid autoencoder trained with MSE against the (normalized) input
itself, Adagrad like the reference's default.
"""

from __future__ import annotations

import argparse

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="Train an MNIST autoencoder")
    p.add_argument("-f", "--folder", default=None,
                   help="MNIST idx dir (default: synthetic)")
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("-e", "--max-epoch", type=int, default=5)
    p.add_argument("--bottleneck", type=int, default=32)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--synthetic-n", type=int, default=2048)
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch, mnist
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.autoencoder import autoencoder

    if args.folder:
        imgs, _ = mnist.load_mnist(args.folder, train=True)
    else:
        imgs, _ = mnist.synthetic_mnist(args.synthetic_n)
    x = imgs.astype(np.float32) / 255.0  # sigmoid output range
    # target = the input itself (reference feeds the image as label too)
    samples = [Sample(x[i], x[i].reshape(-1)) for i in range(len(x))]

    model = autoencoder(class_num=args.bottleneck)
    opt = (optim.LocalOptimizer(model, DataSet.array(samples)
                                >> SampleToMiniBatch(args.batch_size),
                                nn.MSECriterion())
           .set_optim_method(optim.Adagrad(learning_rate=0.01))
           .set_end_when(optim.max_epoch(args.max_epoch)))
    opt.optimize()
    model.training = False
    recon = np.asarray(model.forward(x[:256]))
    mse = float(np.mean((recon - x[:256].reshape(256, -1)) ** 2))
    print(f"final: epoch={opt.state['epoch']} loss={opt.state['loss']:.5f} "
          f"recon_mse={mse:.5f}")
    return opt


if __name__ == "__main__":
    main()
