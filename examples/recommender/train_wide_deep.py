"""Wide&Deep recommendation training.

Analog of the reference's Wide&Deep workload (named in BASELINE.json;
reference-era BigDL serves it via the sparse layer family —
``SparseLinear``/``LookupTableSparse``).  Trains on MovieLens-style
implicit feedback: wide = crossed (user x genre-bucket) sparse
features through SparseLinear, deep = user/item embeddings through an
MLP.

Two wide-feature representations (see ``nn/sparse.py``):
- default: fixed-width id bags (ids + weights arrays);
- ``--sparse-coo``: ragged per-sample sparse features collated into
  batch-COO ``SparseMiniBatch``es (the reference's ``SparseMiniBatch``
  path) executed via segment-sum kernels.
"""

from __future__ import annotations

import argparse

try:
    import bigdl_tpu  # noqa: F401
except ImportError:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="Train Wide&Deep on ratings")
    p.add_argument("-f", "--folder", default=None,
                   help="MovieLens dir with ratings.dat (default: "
                        "synthetic ratings)")
    p.add_argument("-b", "--batch-size", type=int, default=256)
    p.add_argument("-e", "--max-epoch", type=int, default=8)
    p.add_argument("--sparse-coo", action="store_true",
                   help="feed the wide part as batch-COO "
                        "SparseMiniBatches instead of fixed-width bags")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import movielens
    from bigdl_tpu.models.recommender import WideAndDeep

    if args.folder:
        ratings = movielens.load(args.folder)
    else:
        ratings = movielens.synthetic_ratings(n_users=100, n_items=80,
                                              n_ratings=6000)
    users = ratings[:, 0] - 1
    items = ratings[:, 1] - 1
    labels = (ratings[:, 2] >= 4).astype(np.float32)
    n_users = int(users.max()) + 1
    n_items = int(items.max()) + 1

    # wide part: crossed (user, item-bucket) feature ids as 1-hot id bags
    n_buckets = 8
    wide_dim = n_users * n_buckets
    wide_ids = (users * n_buckets + items % n_buckets).astype(np.int32)
    wide_bags = wide_ids[:, None]                  # (N, 1) id bag
    wide_weights = np.ones_like(wide_bags, np.float32)

    model = WideAndDeep(wide_dim=wide_dim,
                        deep_field_counts=[n_users, n_items],
                        embed_dim=16, hidden=(64, 32))
    params, state = model.init(jax.random.PRNGKey(0))

    deep_ids = np.stack([users, items], axis=1).astype(np.int32)
    N = len(labels)

    def loss_fn(p, batch_ix):
        wide_in = (jnp.asarray(wide_bags)[batch_ix],
                   jnp.asarray(wide_weights)[batch_ix])
        out, _ = model.apply(p, state,
                             (wide_in, jnp.asarray(deep_ids)[batch_ix],
                              None))
        pred = out[:, 0]
        yb = jnp.asarray(labels)[batch_ix]
        eps = 1e-7
        return -jnp.mean(yb * jnp.log(pred + eps)
                         + (1 - yb) * jnp.log(1 - pred + eps))

    method = optim.Adam(learning_rate=0.01)
    ostate = method.init_state(params)
    rng = np.random.default_rng(0)
    it = 0
    if args.sparse_coo:
        # ragged sparse wide features -> batch-COO SparseMiniBatch
        from bigdl_tpu.dataset import SparseSample, batch_sparse_samples
        samples = [SparseSample([wide_ids[i]], [1.0], wide_dim,
                                dense=[deep_ids[i]], label=labels[i])
                   for i in range(N)]

        @jax.jit
        def coo_step(p, os_, coo, dids, yb, it):
            def lf(p):
                out, _ = model.apply(p, state, (coo, dids, None))
                pred = out[:, 0]
                eps = 1e-7
                return -jnp.mean(yb * jnp.log(pred + eps)
                                 + (1 - yb) * jnp.log(1 - pred + eps))
            loss, g = jax.value_and_grad(lf)(p)
            p, os_ = method.update(g, p, os_, 0.01, it)
            return p, os_, loss

        for epoch in range(args.max_epoch):
            perm = rng.permutation(N)
            for s in range(0, N - args.batch_size + 1, args.batch_size):
                mb = batch_sparse_samples(
                    [samples[i] for i in perm[s:s + args.batch_size]],
                    nnz_buckets=[args.batch_size])
                coo, dids = mb.input
                params, ostate, loss = coo_step(
                    params, ostate, coo, jnp.asarray(dids),
                    jnp.asarray(mb.target), it)
                it += 1
    else:
        step = jax.jit(jax.value_and_grad(loss_fn))
        update = jax.jit(method.update)
        for epoch in range(args.max_epoch):
            perm = rng.permutation(N)
            for s in range(0, N - args.batch_size + 1, args.batch_size):
                ix = jnp.asarray(perm[s:s + args.batch_size])
                loss, g = step(params, ix)
                params, ostate = update(g, params, ostate, 0.01, it)
                it += 1
    # training AUC-ish: accuracy at 0.5
    all_ix = jnp.arange(N)
    wide_in = (jnp.asarray(wide_bags), jnp.asarray(wide_weights))
    out, _ = model.apply(params, state,
                         (wide_in, jnp.asarray(deep_ids), None))
    acc = float(((np.asarray(out[:, 0]) > 0.5) == labels).mean())
    print(f"final: loss={float(loss):.4f} train_acc={acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
