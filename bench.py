"""Benchmark driver — prints ONE JSON line.

Analog of the reference's throughput harness
``DL/models/utils/DistriOptimizerPerf.scala:56-140`` (synthetic-input
records/sec).  Measures the flagship ResNet-50 ImageNet training step
(fwd+bwd+SGD-momentum update) on the local TPU chip: images/sec/chip —
the BASELINE.json metric.

Config: NHWC, bf16 compute / f32 master params, batch 128, donated
buffers — the best of the layout×batch sweep on v5e (see git history).

Anchors:
- ``vs_baseline`` stays ratioed against the round-1 recorded measurement
  (1945.9 img/s) so rounds are comparable.
- ``mfu`` is images/sec × 3×4.1 GFLOP/img ÷ 197 TFLOP/s (v5e bf16 peak).
  NOTE ResNet-50 training on v5e is HBM-bandwidth-bound, not MXU-bound:
  XLA's cost analysis reports ~79 GB accessed/step at batch 256, i.e. a
  ~96 ms bandwidth floor at 819 GB/s — the measured step time tracks that
  floor at ~90%+, so MFU plateaus near 0.16 by roofline, not by waste.

``--scaling`` mode: runs the DistriOptimizer SPMD step on 1..N virtual CPU
devices and reports parallel efficiency (reference scaling-claim analog,
``docs/docs/whitepaper.md:160-164``).  Run separately; the default mode is
what the driver records.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# round-1 recorded TPU v5 lite measurement (bf16, NCHW, batch 64); later
# rounds report improvement vs this anchor
BASELINE_IMAGES_PER_SEC = 1945.9  # 2026-07-29 r01
PEAK_BF16_FLOPS = 197e12          # v5e MXU peak
TRAIN_GFLOP_PER_IMAGE = 3 * 4.1   # fwd + dgrad + wgrad, ResNet-50/224


def main():
    import jax
    import jax.numpy as jnp
    from functools import partial
    from bigdl_tpu import nn, optim
    from bigdl_tpu.models.resnet import resnet50
    from bigdl_tpu.utils.precision import mixed_precision_loss_fn

    fmt, batch = "NHWC", 128
    model = resnet50(format=fmt)
    criterion = nn.ClassNLLCriterion()
    method = optim.SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)

    params, mstate = model.init(jax.random.PRNGKey(0))
    ostate = method.init_state(params)
    shape = (batch, 224, 224, 3) if fmt == "NHWC" else (batch, 3, 224, 224)
    x = jnp.asarray(np.random.default_rng(0).normal(
        0, 1, shape).astype(np.float32))
    y = jnp.asarray(np.random.default_rng(1).integers(
        0, 1000, (batch,)).astype(np.int32))

    # bf16 compute / f32 master params — the framework's standard mixed
    # precision (utils/precision.py), as used via set_compute_dtype
    base_loss = mixed_precision_loss_fn(model, criterion, jnp.bfloat16)

    def loss_fn(p, ms, x, y):
        return base_loss(p, ms, x, y, None)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(p, ms, os_, x, y, lr, it):
        (loss, ms), g = grad_fn(p, ms, x, y)
        p, os_ = method.update(g, p, os_, lr, it)
        return p, ms, os_, loss

    # warmup/compile.  NOTE: on the experimental 'axon' TPU platform
    # block_until_ready does not actually wait for completion — a host
    # round-trip (float()) is the only reliable sync.
    params, mstate, ostate, loss = step(params, mstate, ostate, x, y, 0.1, 0)
    float(loss)

    iters = 32
    t0 = time.perf_counter()
    for i in range(iters):
        params, mstate, ostate, loss = step(params, mstate, ostate, x, y,
                                            0.1, i)
    float(loss)  # full pipeline sync
    dt = time.perf_counter() - t0
    ips = batch * iters / dt
    mfu = ips * TRAIN_GFLOP_PER_IMAGE * 1e9 / PEAK_BF16_FLOPS

    vs = ips / BASELINE_IMAGES_PER_SEC
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
        "mfu": round(mfu, 4),
        "config": f"{fmt}/bf16/batch{batch}/donated",
    }))


def scaling():
    """Sharding-overhead harness on a virtual CPU mesh.

    True multi-chip weak scaling cannot be measured on one host: the 8
    virtual devices share the same physical cores, so contention would
    masquerade as scaling loss.  What CAN be isolated is the overhead the
    SPMD partitioning itself adds: run the SAME global problem (fixed
    global batch) unsharded on 1 device vs sharded over 8 — identical
    total CPU work, so efficiency = t(1-dev)/t(8-dev) ≈ 1 - collective/
    partition overhead.  The real 1→32-chip ICI measurement (BASELINE
    north star >60%) needs pod hardware the driver doesn't provide."""
    import os
    import subprocess

    results = {}
    for n in (1, 8):
        env = dict(os.environ)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count=8")
        env["XLA_FLAGS"] = " ".join(flags)
        env["_BENCH_SCALING_N"] = str(n)
        out = subprocess.run(
            [sys.executable, __file__, "--scaling-child"], env=env,
            capture_output=True, text=True)
        if out.returncode != 0:
            print(out.stderr, file=sys.stderr)
            raise RuntimeError(f"scaling child n={n} failed")
        results[n] = float(out.stdout.strip().splitlines()[-1])
    eff = round(results[8] / results[1], 3)
    print(json.dumps({
        "metric": "resnet_cifar_sharding_overhead_efficiency_cpu_mesh",
        "value": eff,
        "unit": "parallel_efficiency",
        "images_per_sec": {str(n): round(results[n], 1) for n in results},
    }))


def scaling_child():
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from bigdl_tpu import nn, optim
    from bigdl_tpu.models.resnet import resnet_cifar

    n = int(os.environ["_BENCH_SCALING_N"])
    devs = jax.devices()
    assert len(devs) >= n, (n, devs)
    mesh = Mesh(np.array(devs[:n]), ("data",))

    model = resnet_cifar(depth=20)
    criterion = nn.ClassNLLCriterion()
    method = optim.SGD(learning_rate=0.1, momentum=0.9)
    params, mstate = model.init(jax.random.PRNGKey(0))
    ostate = method.init_state(params)
    batch = 128  # FIXED global batch: same total work for every n
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, 3, 32, 32)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, (batch,)).astype(np.int32))
    data_sh = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())
    x = jax.device_put(x, data_sh)
    y = jax.device_put(y, data_sh)
    params = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), params)
    mstate = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), mstate)
    ostate = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), ostate)

    def loss_fn(p, ms, x, y):
        out, ms2 = model.apply(p, ms, x, training=True)
        return criterion.apply(out, y), ms2

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(p, ms, os_, x, y, it):
        (loss, ms), g = grad_fn(p, ms, x, y)
        p, os_ = method.update(g, p, os_, 0.1, it)
        return p, ms, os_, loss

    params, mstate, ostate, loss = step(params, mstate, ostate, x, y, 0)
    loss.block_until_ready()
    iters = 10
    t0 = time.perf_counter()
    for i in range(iters):
        params, mstate, ostate, loss = step(params, mstate, ostate, x, y, i)
    loss.block_until_ready()
    dt = time.perf_counter() - t0
    print(batch * iters / dt)


if __name__ == "__main__":
    if "--scaling-child" in sys.argv:
        scaling_child()
    elif "--scaling" in sys.argv:
        scaling()
    else:
        main()
