"""Benchmark driver — prints ONE JSON line.

Analog of the reference's throughput harness
``DL/models/utils/DistriOptimizerPerf.scala:56-140`` (synthetic-input
records/sec).  Measures the flagship ResNet-50 ImageNet training step
(fwd+bwd+SGD-momentum update) on the local TPU chip: images/sec/chip —
the BASELINE.json metric.

The reference repo publishes no absolute images/sec numbers (BASELINE.md);
``vs_baseline`` is the ratio against the first TPU measurement recorded
here so later rounds are comparable.
"""

from __future__ import annotations

import json
import time

import numpy as np

# first recorded TPU v5 lite measurement (bf16 compute, batch 64); later
# rounds report improvement vs this anchor
BASELINE_IMAGES_PER_SEC = 1945.9  # 2026-07-29, f32 was ~1000


def main():
    import jax
    import jax.numpy as jnp
    from bigdl_tpu import nn, optim
    from bigdl_tpu.models.resnet import resnet50

    from bigdl_tpu.utils.precision import mixed_precision_loss_fn

    model = resnet50()
    criterion = nn.ClassNLLCriterion()
    method = optim.SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)

    batch = 64
    params, mstate = model.init(jax.random.PRNGKey(0))
    ostate = method.init_state(params)
    x = jnp.asarray(np.random.default_rng(0).normal(
        0, 1, (batch, 3, 224, 224)).astype(np.float32))
    y = jnp.asarray(np.random.default_rng(1).integers(
        0, 1000, (batch,)).astype(np.int32))

    # bf16 compute / f32 master params — the framework's standard mixed
    # precision (utils/precision.py), as used via set_compute_dtype
    base_loss = mixed_precision_loss_fn(model, criterion, jnp.bfloat16)

    def loss_fn(p, ms, x, y):
        return base_loss(p, ms, x, y, None)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @jax.jit
    def step(p, ms, os_, x, y, lr, it):
        (loss, ms), g = grad_fn(p, ms, x, y)
        p, os_ = method.update(g, p, os_, lr, it)
        return p, ms, os_, loss

    # warmup/compile.  NOTE: on the experimental 'axon' TPU platform
    # block_until_ready does not actually wait for completion — a host
    # round-trip (float()) is the only reliable sync.
    params, mstate, ostate, loss = step(params, mstate, ostate, x, y, 0.1, 0)
    float(loss)

    iters = 20
    t0 = time.perf_counter()
    for i in range(iters):
        params, mstate, ostate, loss = step(params, mstate, ostate, x, y,
                                            0.1, i)
    float(loss)  # full pipeline sync
    dt = time.perf_counter() - t0
    ips = batch * iters / dt

    vs = 1.0 if BASELINE_IMAGES_PER_SEC is None \
        else ips / BASELINE_IMAGES_PER_SEC
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
