"""Benchmark driver — prints ONE JSON line.

Analog of the reference's throughput harness
``DL/models/utils/DistriOptimizerPerf.scala:56-140`` (synthetic-input
records/sec).  Runs the flagship model's jit'd training step on the real
TPU chip and reports images/sec/chip.

The reference repo publishes no absolute images/sec numbers
(BASELINE.md) — ``vs_baseline`` is therefore the ratio against a fixed
reference point recorded here (first-round TPU measurement) so rounds are
comparable.
"""

from __future__ import annotations

import json
import time

import numpy as np


# first recorded TPU v5e-1 measurement for this benchmark config; later
# rounds report improvement vs this anchor
BASELINE_IMAGES_PER_SEC = 4879874.5  # TPU v5 lite, batch 1024, 2026-07-29


def main():
    import jax
    import jax.numpy as jnp
    from bigdl_tpu import nn, optim
    from bigdl_tpu.models.lenet import lenet5

    model = lenet5()
    criterion = nn.ClassNLLCriterion()
    method = optim.SGD(learning_rate=0.01, momentum=0.9)

    batch = 1024
    rng = jax.random.PRNGKey(0)
    params, mstate = model.init(rng)
    ostate = method.init_state(params)
    x = jnp.asarray(np.random.default_rng(0).normal(
        0, 1, (batch, 1, 28, 28)).astype(np.float32))
    y = jnp.asarray(np.random.default_rng(1).integers(
        0, 10, (batch,)).astype(np.int32))

    def loss_fn(p, ms, x, y):
        out, new_ms = model.apply(p, ms, x, training=True)
        return criterion.apply(out, y), new_ms

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @jax.jit
    def step(p, ms, os_, x, y, lr, it):
        (loss, ms), g = grad_fn(p, ms, x, y)
        p, os_ = method.update(g, p, os_, lr, it)
        return p, ms, os_, loss

    # warmup/compile
    params, mstate, ostate, loss = step(params, mstate, ostate, x, y, 0.01, 0)
    jax.block_until_ready(loss)

    iters = 50
    t0 = time.perf_counter()
    for i in range(iters):
        params, mstate, ostate, loss = step(params, mstate, ostate, x, y,
                                            0.01, i)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    ips = batch * iters / dt

    vs = 1.0 if BASELINE_IMAGES_PER_SEC is None \
        else ips / BASELINE_IMAGES_PER_SEC
    print(json.dumps({
        "metric": "lenet5_train_images_per_sec_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
