"""Benchmark driver — prints ONE JSON line.

Analog of the reference's throughput harness
``DL/models/utils/DistriOptimizerPerf.scala:56-140`` (synthetic-input
records/sec).  Measures a five-model menu on the local TPU chip, all
as full training steps (fwd+bwd+optimizer update): the two
BASELINE.json models — ResNet-50 and Inception-v1 (images/sec/chip) —
plus, since round 5, VGG-16 (images/sec; the conv-heavy regression
sentinel), the PTB "medium" LSTM (words/sec; the scan-heavy one), and
a census-dims Wide&Deep (records/sec; the sparse-embedding one —
COO wide features + embedding bags, the BASELINE.json recommender
config family).
ResNet-50 failing aborts the capture (it is the headline metric); a
failure in any secondary model records a ``<model>_error`` key and the
rest of the capture survives.

Config: NHWC, bf16 compute / f32 master params, batch 256, donated
buffers — best of the layout×batch×remat sweep on v5e (see git
history; batch 512 regresses ~6% past its own bandwidth floor from
memory pressure, FULL per-block remat costs ~20% because recomputed
convs re-read activations).

Integrity discipline (round-5, VERDICT r4 item 1):
- ``toolchain`` stamps jax/jaxlib versions + platform/device into every
  emitted JSON: r3→r4 showed cross-round numbers are toolchain-
  confounded (jax 0.8→0.9 moved ResNet's compiled step from 78.7 to
  ~85 GB/step with IDENTICAL source — a 5% throughput drop that is a
  compiler property, not a code property).
- AOT compile / cost-analysis failure is NEVER silent: the JSON either
  carries ``bottleneck`` + ``mfu`` or a ``cost_analysis_error`` string,
  and ``timing_path`` says whether the timing loop ran the AOT
  executable or fell back to jit dispatch.
- every measured window ends with a host sync that ASSERTS the loss is
  finite — a NaN-producing step can't post a throughput number.
- ``value`` is the MEDIAN over ``windows`` independent timing windows
  (the r4 definition); ``best_window`` is also reported as the bridge
  to r2/r3, whose ``value`` was best-of-4.

``bottleneck`` is TRACE-BACKED, not asserted: XLA's compiled-executable
cost analysis (flops + bytes accessed) gives the MXU-time and HBM-time
floors; the measured step time is compared against both.  ``mfu`` uses
the XLA-counted flops over the 197 TFLOP/s v5e bf16 peak (XLA counts
2 flops/MAC — the same convention as the spec number).

``chip_gate`` (round-5, VERDICT r4 item 2): the pytest suite pins CPU
by design, so the bench — the one thing that touches the real chip —
now also proves the chip computes CORRECT numbers: it trains LeNet and
ResNet-CIFAR on-device via the example entry scripts with the exact
flags and bars of the CPU suite gates
(``tests/test_accuracy_gates.py::test_lenet_synthetic_accuracy_gate``:
val top-1 ≥ 0.99; ``tests/test_zoo_recipes.py::test_resnet_cifar_recipe``:
final loss < 2.0) and additionally asserts the logged loss DECREASED
from the first iteration.  Mirrors the reference testing its real
engine end-to-end (``TEST/optim/DistriOptimizerSpec.scala:139``).

``dispatch_overhead_fraction`` (round-6): PTB-LSTM and Wide&Deep sit at
0.98/0.64 of their HBM floor yet posted 21.6%/24.0% window spread in r5
— their 3-9 ms steps are short enough that per-step host dispatch (and
the per-step ``float(loss)`` sync the old driver did) IS the gap.  The
bench now measures each of them twice — classic step-per-dispatch vs a
K=8 ``lax.scan``-fused block (the bench mirror of the driver's
``steps_per_dispatch``) — and reports
``1 - t_fused_step/t_unfused_step`` per model from the window medians
(negative values = fusion lost; never clamped).  Caveat recorded as
``*_cost_note``: XLA's cost analysis counts a scan body ONCE, so a
fused block's flops/bytes read as ≈ per-step, not per-block.

``collective_overhead_fraction`` (round-5, VERDICT r4 item 3): the r4
1-vs-8 "scaling efficiency" proxy measured cache effects (1.28 on one
core — physically meaningless as a collective gate).  Replaced by a
DIRECT ablation on the 8-device CPU mesh: the same shard_map DP
training step timed with the gradient all-reduce present vs ablated —
identical per-device compute, so the delta IS the collective cost.
Calibration notes (measured on this box, 2026-07-30): ResNet-20's
0.27M params make the psum invisible inside ±5% step noise, so the
workload is a deliberately param-heavy MLP (3×2048² ≈ 12.6M params,
50 MB/psum) where the host-emulated all-reduce is unambiguous.  Two
independent calibration runs: ablated 598/616 ms/step, with 879/866
(fraction 0.32/0.29), 3 injected extra all-reduces 1140/1123
(fraction 0.48/0.45).  Gate: fraction ≤ 0.38 — above the measured
band, below the injected band, ~2 extra all-reduces trip it — and a
SELF-TEST
run with the 3 extra all-reduces must itself VIOLATE the gate, proving
on every bench run that the gate can fail (VERDICT r4's "done"
criterion).  The absolute fraction is a property of the host-mesh
emulation (ICI is ~100× faster than host-memory loopback), so the
gate is a round-over-round regression tripwire, not an efficiency
claim; the real >60%-at-32-chips claim (whitepaper.md:160-164) needs
pod hardware.  The old 1-vs-8 number is kept informational only and
values > 1.05 are flagged ``measurement_error`` (super-linear
"scaling" on one physical core means cache effects dominate).

Round-7 (grad_sync wire formats): the collective entry now times the
explicit ``parallel/grad_sync.py`` step (bucketed reduce-scatter →
owned-slice update → all-gather) with f32 and bf16 wires alongside the
legacy psum modes, reporting ``collective_overhead_fraction_by_wire``
and each compiled child's ``collective_wire_bytes`` (per-op-kind
payload from ``tools.byte_audit.collective_wire_bytes``).  CPU-host
caveat, measured 2026-08-03: XLA's CPU backend CONVERTS sub-f32
collectives to f32 (a ``convert`` fusion brackets the reduce-scatter)
and host-emulates the stochastic-rounding RNG, so on this mesh the
bf16 wire shows f32 bytes and a ~2.4× slowdown — the numbers are
honest properties of the emulation, not of the wire format; the
bf16-halves-bytes invariant is gated on canned HLO in
``tests/test_byte_audit.py`` and the real effect needs the chip.
Also round-7: per-workload production ``steps_per_dispatch`` defaults
live in ``PRODUCTION_K`` (PTB-LSTM/Wide&Deep K=8, conv nets K=1 —
closes the ROADMAP K-defaults item), jittery entries discard 2 warmup
windows, and ``_stats`` adds a ``trimmed_median`` (min/max window
dropped) that derived fractions read.

Round-8 (telemetry): every ``_measure`` entry now reports
``*_pipeline_phases`` — host-dispatch vs pipeline-drain vs other time
shares from telemetry tracer spans over the measured windows — so
bottleneck attribution carries the pipeline picture alongside the
MXU/HBM floors; the 1v8 scaling child excludes compile/warmup and
unsteady (cache-effect/jitter) windows from its steady-state rate via
per-window spans and records the excluded fraction per mesh size
(``steady_state_filter`` — the r05 ``measurement_error`` fix: the flag
is still computed, but the number behind it is now auditable); the
serving sweep adds per-row-bucket latency (``latency_ms_by_bucket``).

Round-9 (checkpointing): ``bench.py --checkpoint`` runs the same small
training with checkpointing off / synchronous / async and records
``checkpoint_stall_fraction`` (driver-side checkpoint seconds over run
wall, from the ``checkpoint/stall_fraction`` registry gauge) plus
per-snapshot driver-stall and writer-commit times — the async path's
claim ("snapshots cost the driver a capture + enqueue, not a
serialize+CRC+fsync") as a recorded number (CPU smoke 2026-08-03:
sync 0.81 fraction / 330 ms per snapshot inline vs async 0.02 / 3.5
ms; the bitwise-inertness hard gate lives in tests/test_checkpoint.py).

Round-10 (fused kernels): ``ptb_lstm_fused_cell`` and
``wide_deep_fused_bag`` measure the two HBM-floor workloads with the
pallas custom kernels engaged (fused LSTM cell, fused COO
embedding-bag — ops/pallas_lstm.py, ops/pallas_embed.py) and
``fused_kernel_bytes`` records bytes/step + hbm_floor_fraction deltas
vs the XLA baselines.  CPU-host caveat, recorded 2026-08-03: off-TPU
the kernels run in pallas INTERPRET mode (XLA emulation of the kernel
body), so their throughput and cost-analysis numbers are
correctness-only, not perf — the strictly-lower-bytes claim is gated
on canned HLO in tests/test_byte_audit.py and the on-chip capture is
carried measurement debt.

Round-4 experiment log (all medians over ≥5 windows, v5e, batch 256;
r3 baseline ResNet-50 2499.7 img/s / 78.7 GB/step under jax 0.8,
Inception-v1 4645 / 37.3 GB/step):
- remat="tails" (save conv outputs, recompute BN/ReLU): 2160 img/s,
  bytes 92.5 GB — XLA's own saved-residual choice already beats the
  forced policy, and checkpoint boundaries block cross-block fusion.
- full per-block remat: ~20% slower (r3).
- batch 384: 2442 img/s, floor-fraction drops 0.94→0.84 (memory
  pressure); batch 512 worse still (r2).
- bf16 stochastic-rounded momentum: 2443 img/s, bytes 79.5 GB —
  optimizer state is 0.26% of step traffic; kept as a memory-capacity
  option (SGD state_dtype).
- maxpool backward (select-and-scatter) replacements: ablations show
  S&S wastes ~8.6 ms/step on Inception (pool-stubbed model runs at
  96.8% of its floor vs 82.6% real), but every alternative loses more:
  XLA phase decomposition 67.8 GB, pallas first-match kernel 80.4 GB
  (layout copies: pallas can't accept XLA's batch-minor layouts),
  hand-written custom-vjp 95.9 GB.  See nn/layers.py SpatialMaxPooling
  and ops/pallas_pool.py.
Round-5 log lives in BASELINE.md §"jax 0.9 floor shift".
"""

from __future__ import annotations

import json
import math
import os
import re
import statistics
import sys
import time

import numpy as np

# round-1 recorded TPU v5 lite measurement (bf16, NCHW, batch 64); later
# rounds report improvement vs this anchor.  NOTE the anchor was taken
# under jax 0.8 — the `toolchain` stamp exists precisely because this
# ratio is toolchain-confounded across rounds.
BASELINE_IMAGES_PER_SEC = 1945.9  # 2026-07-29 r01
PEAK_BF16_FLOPS = 197e12          # v5e MXU peak
HBM_BYTES_PER_SEC = 819e9         # v5e HBM bandwidth

ROOT = os.path.dirname(os.path.abspath(__file__))

# Production steps_per_dispatch per workload (round-7, closes the
# ROADMAP "pick K defaults" item).  Chosen from the round-6
# dispatch_overhead_fraction ablation: PTB-LSTM (3-5 ms steps) and
# Wide&Deep (~9 ms) are host-dispatch-bound — K=8 recovers the
# measured per-step dispatch tax and is where the fused curve flattens
# (K=16 measured within noise of K=8 with 2× the staging latency at
# trigger boundaries).  The conv nets run 35-100 ms steps at 0.82-0.95
# of their HBM floor — dispatch is invisible there, and K>1 only
# delays trigger/validation boundaries, so they stay at K=1.
_HAND_TUNED_K = {
    "resnet50": 1, "inception_v1": 1, "vgg16": 1,
    "ptb_lstm": 8, "wide_deep": 8,
}


class _ProductionK(dict):
    """Deprecation shim (round-11, the autotuner PR): per-workload
    production ``steps_per_dispatch`` now prefers the autotuned
    ``tuned_configs.json`` entry for the live backend
    (``tools/autotune.py`` output, read through
    ``bigdl_tpu.utils.tuned``), falling back to the hand-maintained
    round-7 dict this object still carries.  ``PRODUCTION_K[w]`` keeps
    its historical int semantics; ``PRODUCTION_K.source(w)`` returns
    ``(k, "tuned_configs.json" | "hand")`` and the capture JSON records
    the source per entry (``dispatch_fuse_k_source``)."""

    def source(self, workload):
        try:
            from bigdl_tpu.utils.tuned import lookup
            v = lookup(workload, "steps_per_dispatch")
        except Exception:
            v = None  # tuned layer unavailable != bench unavailable
        if v is not None:
            return int(v), "tuned_configs.json"
        return dict.__getitem__(self, workload), "hand"

    def __getitem__(self, workload):
        return self.source(workload)[0]


PRODUCTION_K = _ProductionK(_HAND_TUNED_K)


def _toolchain():
    """Version/platform stamp embedded in every emitted JSON."""
    import jax
    import jaxlib
    dev = jax.devices()[0]
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "unknown"),
    }


def _measure(model, batch: int, windows: int = 6, iters: int = 32,
             x=None, y=None, criterion=None, units_per_step=None,
             compute_dtype=None, fuse_k=None, warmup_windows: int = 0,
             activation_memory=None):
    """Compile + run one training step.

    Default inputs are the ImageNet-shaped NHWC batch; recurrent/other
    models pass explicit ``x``/``y``/``criterion``.  ``units_per_step``
    is the throughput numerator (images for conv nets, words for LMs;
    defaults to ``batch``).

    ``warmup_windows``: extra leading timing windows that run the full
    protocol (finite-loss assert included) but post no sample — the
    round-7 jitter fix for the short-step entries.

    ``activation_memory``: the remat slice of the driver's
    ``set_activation_memory`` policies — ``None``/``"none"`` (store
    everything), ``"dots"`` (save matmul outputs, recompute the
    elementwise chain) or ``"full"`` (save step inputs only), applied
    with the SAME ``jax.checkpoint`` policies the optimizer uses so
    autotuner trials measure the real knob.  The bf16 storage variants
    are expressed through ``compute_dtype`` here, not this arg.

    ``fuse_k``: fuse ``K`` consecutive steps into one jit dispatch via
    ``lax.scan`` over a K-stacked input — the bench-side mirror of the
    driver's ``steps_per_dispatch`` fusion.  The same batch is reused
    for every step of a block (timing, not learning), the per-step work
    is identical, and the reported units/s stay per ORIGINAL step, so
    unfused-vs-fused medians isolate the host dispatch overhead.

    Returns ``(per-window units/s list, cost-analysis dict,
    timing_path)`` where cost-analysis is either ``{"flops", "bytes"}``
    (≈ per step even for a fused block — XLA's cost analysis counts a
    scan body ONCE, so the block's totals are NOT divided by K; the
    caveat rides along as a ``note`` key / ``*_cost_note``) or
    ``{"error": <msg>}`` — never silently empty — and ``timing_path``
    records whether the timing loop ran the AOT executable or jit
    dispatch.  Raises if any measured window ends with a non-finite
    loss.
    """
    import jax
    import jax.numpy as jnp
    from functools import partial
    from bigdl_tpu import nn, optim
    from bigdl_tpu.utils.precision import mixed_precision_loss_fn

    criterion = criterion or nn.ClassNLLCriterion()
    units_per_step = units_per_step or batch
    method = optim.SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)
    params, mstate = model.init(jax.random.PRNGKey(0))
    ostate = method.init_state(params)
    if x is None:
        x = jnp.asarray(np.random.default_rng(0).normal(
            0, 1, (batch, 224, 224, 3)).astype(np.float32))
        y = jnp.asarray(np.random.default_rng(1).integers(
            0, 1000, (batch,)).astype(np.int32))

    base_loss = mixed_precision_loss_fn(model, criterion,
                                        compute_dtype or jnp.bfloat16)
    if activation_memory not in (None, "none"):
        if activation_memory not in ("dots", "full"):
            raise ValueError(
                f"activation_memory must be None|'none'|'dots'|'full' "
                f"here (bf16 storage rides compute_dtype), got "
                f"{activation_memory!r}")
        base_loss = jax.checkpoint(
            base_loss,
            policy=(jax.checkpoint_policies.dots_saveable
                    if activation_memory == "dots"
                    else jax.checkpoint_policies.nothing_saveable))
    grad_fn = jax.value_and_grad(base_loss, has_aux=True)
    rng0 = jax.random.PRNGKey(42)  # dropout rng (Inception-v1 trains one)

    if fuse_k:
        K = int(fuse_k)
        tstack = jax.tree_util.tree_map
        x = tstack(lambda a: jnp.stack([a] * K), x)
        y = tstack(lambda a: jnp.stack([a] * K), y)
        rngs0 = jnp.stack([rng0] * K)

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(p, ms, os_, xs, ys, lr, it0, rngs):
            def body(carry, inp):
                p, ms, os_ = carry
                xk, yk, itk, rngk = inp
                (loss, ms), g = grad_fn(p, ms, xk, yk, rngk)
                p, os_ = method.update(g, p, os_, lr, itk)
                return (p, ms, os_), loss
            its = it0 + jnp.arange(K, dtype=jnp.int32)
            (p, ms, os_), losses = jax.lax.scan(
                body, (p, ms, os_), (xs, ys, its, rngs))
            return p, ms, os_, losses[-1]

        rng0 = rngs0
        dispatches = max(1, iters // K)
        # XLA's compiled cost analysis counts a while/scan BODY once
        # (trip counts are not folded in — verified: an 8-fused block
        # reports the same flops as one unfused step), so the block's
        # numbers already read as ≈ per-step; do NOT divide by K.
        ca_note = ("scan body counted once by XLA cost analysis; "
                   "values are ~per-step, not per-block")
    else:
        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(p, ms, os_, x, y, lr, it, rng):
            (loss, ms), g = grad_fn(p, ms, x, y, rng)
            p, os_ = method.update(g, p, os_, lr, it)
            return p, ms, os_, loss

        dispatches = iters
        ca_note = None
    steps_per_dispatch = iters // dispatches if not fuse_k else int(fuse_k)

    # ONE compile: the AOT executable serves both cost_analysis and the
    # timing loop (a separate jit dispatch would compile a second time).
    # Failure here is NOT allowed to be silent (VERDICT r4 weak#1: the
    # r4 BENCH capture lost mfu/bottleneck to an `except: pass`).
    run = step
    timing_path = "aot"
    try:
        compiled = step.lower(params, mstate, ostate, x, y, 0.1, 0,
                              rng0).compile()
        c = compiled.cost_analysis()
        if isinstance(c, list):
            c = c[0]
        ca = {"flops": float(c.get("flops", 0.0)),
              "bytes": float(c.get("bytes accessed", 0.0))}
        if ca_note:
            ca["note"] = ca_note
        run = compiled
    except Exception as e:  # recorded in the JSON, never dropped
        ca = {"error": f"{type(e).__name__}: {e}"}
        timing_path = "jit_dispatch"

    # warmup.  NOTE: on the experimental 'axon' TPU platform
    # block_until_ready does not actually wait for completion — a host
    # round-trip (float()) is the only reliable sync.
    params, mstate, ostate, loss = run(params, mstate, ostate, x, y,
                                       np.float32(0.1), np.int32(0), rng0)
    float(loss)

    # warmup-window discard (round-7): the first measured windows after
    # compile carry allocator/page-in noise — on the short-step entries
    # (PTB, Wide&Deep) that alone produced 0.22-0.24 rel_spread, enough
    # to drown a wire-compression delta.  Discarded windows run the
    # full timing protocol (finite-loss assert included) but never post
    # a sample.
    #
    # Pipeline-phase attribution (round-8, the telemetry PR): the
    # measured windows run under a telemetry tracer — span per dispatch
    # enqueue, span per end-of-window pipeline drain (the float(loss)
    # sync) — so each entry reports where its wall time went alongside
    # the MXU/HBM floors: ``dispatch`` is host enqueue time (including
    # backpressure when the in-flight queue is deep), ``device_wait``
    # the window-end drain, ``other`` device-bound time the host spent
    # inside neither.  Spans are two clock reads each — the timing
    # numbers are unchanged (the tracer is disabled during warmup too,
    # same discipline as the sample discard).
    from bigdl_tpu.telemetry import Tracer
    tracer = Tracer(enabled=False)
    samples = []
    wall_measured = 0.0
    for w in range(warmup_windows + windows):
        tracer.enabled = w >= warmup_windows
        t0 = time.perf_counter()
        for i in range(dispatches):
            with tracer.span("dispatch", cat="dispatch"):
                params, mstate, ostate, loss = run(
                    params, mstate, ostate, x, y, np.float32(0.1),
                    np.int32((w * dispatches + i) * steps_per_dispatch),
                    rng0)
        with tracer.span("device_wait", cat="device_wait"):
            lv = float(loss)  # full pipeline sync
        if not math.isfinite(lv):
            raise RuntimeError(
                f"non-finite loss {lv} at end of measured window {w} — "
                f"refusing to report a throughput number for a broken "
                f"computation")
        if w >= warmup_windows:
            dt = time.perf_counter() - t0
            wall_measured += dt
            samples.append(units_per_step * dispatches * steps_per_dispatch
                           / dt)
    if wall_measured > 0:
        totals = tracer.phase_totals()
        shares = {k: round(v / wall_measured, 4)
                  for k, v in sorted(totals.items())}
        shares["other"] = round(
            max(0.0, 1.0 - sum(shares.values())), 4)
        ca["pipeline_phases"] = shares
    return samples, ca, timing_path


def _stats(samples):
    med = statistics.median(samples)
    out = {
        "median": round(med, 1),
        "min": round(min(samples), 1),
        "max": round(max(samples), 1),
        "rel_spread": round((max(samples) - min(samples)) / med, 4),
        "windows": len(samples),
    }
    if len(samples) >= 5:
        # trimmed median (round-7): drop the single best and worst
        # window before taking the median — one outlier window (host
        # jitter on 3-9 ms steps) stops dragging the summary; derived
        # comparisons (dispatch_overhead_fraction) read this key
        trimmed = sorted(samples)[1:-1]
        out["trimmed_median"] = round(statistics.median(trimmed), 1)
    return med, out


UNSTEADY_TOL = 0.15  # relative deviation from the reference window rate


def steady_windows(samples, tol=UNSTEADY_TOL, min_samples=3):
    """The PR 6 steady-state window filter, shared by ``scaling_child``
    and ``tools/autotune.py`` (ONE implementation so the two exclusion
    accountings stay comparable): reference = trimmed median (single
    best/worst window dropped) at >= 3 samples, plain median below;
    kept = samples within ``tol`` relative deviation of the reference.

    Returns ``(kept, excluded, ref)``.  ``excluded`` is counted even
    when NOTHING survives — callers then score on ``ref``, never on a
    silently-unfiltered set.  Below ``min_samples`` the filter does not
    act (excluded = 0: one or two windows carry no spread to reason
    about; the autotuner raises this to 4 because its early rungs
    accumulate one window at a time)."""
    samples = list(samples)
    if len(samples) < min_samples:
        return samples, 0, (statistics.median(samples) if samples
                            else 0.0)
    ref = statistics.median(sorted(samples)[1:-1]) if len(samples) >= 3 \
        else statistics.median(samples)
    kept = [s for s in samples if abs(s - ref) <= tol * ref]
    return kept, len(samples) - len(kept), ref


def _bottleneck(ca, ips, batch, peak=PEAK_BF16_FLOPS):
    """Roofline comparison of the measured step vs the compiled
    executable's XLA-counted flop and byte floors."""
    step_ms = batch / ips * 1e3
    t_mxu = ca["flops"] / peak * 1e3
    t_hbm = ca["bytes"] / HBM_BYTES_PER_SEC * 1e3
    return {
        "kind": "hbm" if t_hbm > t_mxu else "mxu",
        "xla_flops_G": round(ca["flops"] / 1e9, 1),
        "xla_bytes_GB": round(ca["bytes"] / 1e9, 2),
        "t_mxu_floor_ms": round(t_mxu, 2),
        "t_hbm_floor_ms": round(t_hbm, 2),
        "t_measured_ms": round(step_ms, 2),
        "hbm_floor_fraction": round(t_hbm / step_ms, 3),
    }


# ------------------------------------------------------------ chip gate
_ITER_RE = re.compile(r"epoch \d+ iter (\d+) loss (\S+)")


def _run_example(script, *args, timeout=2400):
    """Run an example training script ON THE DEFAULT PLATFORM (the real
    chip when present — deliberately NO --cpu flag) and parse the
    final: line plus the first/last per-iteration logged losses."""
    import subprocess
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # don't leak a CPU-mesh device count
    try:
        r = subprocess.run([sys.executable, os.path.join(ROOT, script),
                            *args], capture_output=True, text=True,
                           timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return {"error": f"{script}: timed out after {timeout}s"}
    if r.returncode != 0:
        return {"error": f"{script} rc={r.returncode}: "
                         f"{r.stderr[-800:]}"}
    out = {}
    for line in r.stdout.splitlines():
        if line.startswith("final:"):
            for kv in line.split()[1:]:
                k, _, v = kv.partition("=")
                try:
                    out[k] = float(v)
                except ValueError:
                    pass
    # the CHILD's device (LocalOptimizer logs "device=<dev>") — the
    # parent's platform says nothing about where the child trained
    m = re.search(r"device=([^\n]+)", r.stderr)
    if m:
        out["device"] = m.group(1).strip()
    iters = _ITER_RE.findall(r.stderr)
    try:
        if iters:
            out["first_iter_loss"] = float(iters[0][1])
            out["last_iter_loss"] = float(iters[-1][1])
    except ValueError:
        pass  # unparseable loss token: fall through to the bar checks
    if not out:
        out = {"error": f"{script}: no final/iter lines parsed"}
    return out


def _chip_gate():
    """Train on the real chip with the CPU suite's exact gate recipes;
    PASS needs the same bars, a first→last loss decrease, AND — when
    this process sees a TPU — child-logged evidence that the children
    trained on it too (a dropped tunnel must not masquerade as a
    chip-validated pass)."""
    gate = {"platform": _toolchain()["platform"]}
    lenet = _run_example("examples/lenet/train.py", "-e", "3",
                         "--synthetic-n", "4096", "-b", "128")
    gate["lenet"] = lenet
    lenet_ok = ("error" not in lenet
                and lenet.get("val_top1", 0.0) >= 0.99
                and lenet.get("last_iter_loss", float("inf"))
                < lenet.get("first_iter_loss", 0.0))
    resnet = _run_example("examples/resnet/train_cifar10.py", "-e", "2",
                          "--synthetic-n", "512", "-b", "64")
    gate["resnet_cifar"] = resnet
    resnet_ok = ("error" not in resnet
                 and resnet.get("loss", float("inf")) < 2.0
                 and resnet.get("last_iter_loss", float("inf"))
                 < resnet.get("first_iter_loss", 0.0))
    gate["lenet_top1"] = lenet.get("val_top1")
    on_chip = ("TPU" in str(lenet.get("device", "")).upper()
               and "TPU" in str(resnet.get("device", "")).upper())
    gate["on_chip"] = on_chip
    chip_consistent = on_chip or gate["platform"] != "tpu"
    gate["pass"] = bool(lenet_ok and resnet_ok and chip_consistent)
    return gate


# ----------------------------------------------- collective overhead
def _cpu_mesh_env(n=8, **extra):
    """Env for a CPU-mesh child: strip any inherited device-count flag,
    then force an n-device host platform."""
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    env["XLA_FLAGS"] = " ".join(flags)
    env.update(extra)
    return env


def _collective_child_run(mode):
    """One collective-ablation child; returns the parsed JSON dict
    (``{"ms": ..., "wire_bytes": {...}}``) or None on failure."""
    out = subprocess_run([sys.executable, __file__, "--collective-child"],
                         env=_cpu_mesh_env(_BENCH_COLL_MODE=mode),
                         parse=json.loads)
    if out is not None and not isinstance(out, dict):
        print(f"collective child {mode}: non-dict output {out!r}",
              file=sys.stderr)
        return None
    return out


COLLECTIVE_GATE = 0.38  # calibration in module doc


def _collective_overhead():
    """Direct collective-cost ablation (module doc), round-7 extended to
    the grad_sync wire formats: alongside the legacy psum modes, two
    children run the explicit reduce-scatter → sharded-update →
    all-gather step of ``parallel/grad_sync.py`` with an f32 and a bf16
    wire, and every child reports its compiled program's bytes-on-wire
    from ``tools.byte_audit.collective_wire_bytes`` — so the JSON
    carries ``collective_overhead_fraction`` per wire dtype AND the
    payload reduction that explains it.  The legacy psum gate/self-test
    is unchanged; a failed grad_sync child records an error string
    without dropping the capture."""
    res = {}
    for mode in ("ablated", "with", "inject"):
        r = _collective_child_run(mode)
        if r is None:
            return None
        res[mode] = r
    gs_err = {}
    for mode in ("gs_f32", "gs_bf16"):
        r = _collective_child_run(mode)
        if r is None:
            gs_err[mode] = "grad_sync collective child failed"
        else:
            res[mode] = r
    t_abl = res["ablated"]["ms"]
    frac = lambda m: (res[m]["ms"] - t_abl) / res[m]["ms"]  # noqa: E731
    frac_inj = frac("inject")
    # self-test: the run with 3 injected extra all-reduces must itself
    # VIOLATE the gate — otherwise the gate has no discriminating power
    # and must read red regardless of the real fraction
    selftest = frac_inj > COLLECTIVE_GATE
    by_wire = {}
    for mode, wire in (("with", "psum_f32"), ("gs_f32", "f32"),
                       ("gs_bf16", "bf16")):
        if mode in res:
            by_wire[wire] = round(frac(mode), 4)
    out = {
        "collective_overhead_fraction": round(frac("with"), 4),
        "collective_overhead_fraction_by_wire": by_wire,
        "collective_step_ms": {k: round(v["ms"], 2)
                               for k, v in res.items()},
        "collective_wire_bytes": {k: v["wire_bytes"]
                                  for k, v in res.items()
                                  if v.get("wire_bytes")},
        "collective_gate_0p38": "pass"
                                if (selftest
                                    and frac("with") <= COLLECTIVE_GATE)
                                else "FAIL",
        "collective_selftest_injected_fraction": round(frac_inj, 4),
        "collective_selftest": "pass" if selftest else "FAIL",
    }
    if gs_err:
        out["collective_grad_sync_errors"] = gs_err
    return out


def _scaling_efficiency():
    """INFORMATIONAL 1-vs-8 virtual-CPU-mesh number (r4's proxy).  On
    one physical core this mostly measures cache effects — r4 recorded
    a physically-impossible 1.28 — so it no longer gates anything;
    values > 1.05 are flagged as measurement error.

    Round-8 (telemetry PR, ROADMAP item 4 "fix the scaling bench"): the
    child now measures per-window spans under the telemetry tracer and
    excludes compile/warmup windows plus unsteady outlier windows (the
    cache-effect / host-jitter windows that produced the impossible r05
    number) from the steady-state rate; the EXCLUDED FRACTION rides in
    the capture per mesh size, so any remaining flag is auditable —
    a high excluded fraction means the box couldn't produce a steady
    window and the ratio should not be trusted."""
    results = {}
    for n in (1, 8):
        out = subprocess_run([sys.executable, __file__, "--scaling-child"],
                             env=_cpu_mesh_env(_BENCH_SCALING_N=str(n)),
                             parse=json.loads)
        if out is None:
            return None
        results[n] = out
    value = round(results[8]["ips"] / results[1]["ips"], 3)
    return {
        "value": value,
        "measurement_error": value > 1.05,
        "images_per_sec": {str(n): round(v["ips"], 1)
                           for n, v in results.items()},
        "steady_state_filter": {
            str(n): {k: v[k] for k in ("windows_total", "windows_warmup",
                                       "windows_excluded",
                                       "excluded_fraction")}
            for n, v in results.items()},
    }


def subprocess_run(cmd, env, timeout=1200, parse=float):
    """Run a child, parse its last stdout line with ``parse`` (float for
    the legacy scalar children, ``json.loads`` for the collective
    children)."""
    import subprocess
    try:
        out = subprocess.run(cmd, env=env, capture_output=True, text=True,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        print(f"child timed out after {timeout}s: {cmd}", file=sys.stderr)
        return None
    if out.returncode != 0:
        print(out.stderr[-2000:], file=sys.stderr)
        return None
    try:
        return parse(out.stdout.strip().splitlines()[-1])
    except (IndexError, ValueError):
        # a zero-exit child with unparseable stdout degrades to the
        # recorded-FAIL path, same as a crash (ADVICE r4 #4)
        print(f"unparseable child stdout: {out.stdout[-500:]!r}",
              file=sys.stderr)
        return None


def main(argv):
    from bigdl_tpu.models.resnet import resnet50
    from bigdl_tpu.models.inception import inception_v1

    smoke = "--smoke" in argv
    windows, iters = (1, 4) if smoke else (6, 32)
    batch = 256
    remat = "tails" if "--remat-tails" in argv else (
        True if "--remat-full" in argv else False)
    r_samples, r_ca, r_path = _measure(resnet50(format="NHWC", remat=remat),
                                       batch, windows, iters)
    r_ips, r_spread = _stats(r_samples)

    # bench-level registry (telemetry round 2): every workload's
    # measured pipeline-phase shares land here as gauges, and the
    # capture embeds the end-of-run scalars() snapshot under
    # "telemetry" — the same shape a /metrics scrape exports
    from bigdl_tpu.telemetry import MetricRegistry
    bench_reg = MetricRegistry()

    def _mirror_phases(prefix_, phases_):
        for cat, frac in (phases_ or {}).items():
            bench_reg.gauge(f"bench/{prefix_}_{cat}_fraction").set(frac)

    out = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(r_ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(r_ips / BASELINE_IMAGES_PER_SEC, 3),
        "best_window": round(max(r_samples), 1),  # r2/r3 metric bridge
        "spread": r_spread,
        "toolchain": _toolchain(),
        "timing_path": r_path,
        "config": f"NHWC/bf16/batch{batch}/donated"
                  + (f"/remat-{remat}" if remat else ""),
    }
    phases = r_ca.pop("pipeline_phases", None)
    if phases:
        out["pipeline_phases"] = phases
        _mirror_phases("resnet50", phases)
    if "error" in r_ca:
        out["cost_analysis_error"] = r_ca["error"]
    else:
        out["mfu"] = round(r_ips * (r_ca["flops"] / batch)
                           / PEAK_BF16_FLOPS, 4)
        out["bottleneck"] = _bottleneck(r_ca, r_ips, batch)
    if "--resnet-only" in argv:
        out["telemetry"] = bench_reg.scalars()
        print(json.dumps(out))
        return

    def emit(prefix, metric_key, samples, ca, path, units_per_step,
             peak=PEAK_BF16_FLOPS):
        ups, spread = _stats(samples)
        out[metric_key] = round(ups, 1)
        out[f"{prefix}_best_window"] = round(max(samples), 1)
        out[f"{prefix}_spread"] = spread
        phases = ca.pop("pipeline_phases", None)
        if phases:
            out[f"{prefix}_pipeline_phases"] = phases
            _mirror_phases(prefix, phases)
        if "error" in ca:
            out[f"{prefix}_cost_analysis_error"] = ca["error"]
        else:
            out[f"{prefix}_mfu"] = round(
                ups * (ca["flops"] / units_per_step) / peak, 4)
            out[f"{prefix}_bottleneck"] = _bottleneck(
                ca, ups, units_per_step, peak)
            if "note" in ca:
                out[f"{prefix}_cost_note"] = ca["note"]
        if path != "aot":
            out[f"{prefix}_timing_path"] = path

    def emit_guarded(prefix, metric_key, units_per_step, measure,
                     peak=PEAK_BF16_FLOPS):
        """A secondary model's failure must not discard the primary
        metrics already measured (the r4 lost-capture failure mode)."""
        try:
            samples, ca, path = measure()
            emit(prefix, metric_key, samples, ca, path, units_per_step,
                 peak)
        except Exception as e:
            out[f"{prefix}_error"] = f"{type(e).__name__}: {e}"

    emit_guarded(
        "inception", "inception_v1_images_per_sec_per_chip", batch,
        lambda: _measure(inception_v1(format="NHWC"), batch, windows,
                         iters))

    # reference perf-driver menu breadth (DistriOptimizerPerf.scala:56-140
    # offers vgg16 alongside the conv nets; a recurrent model rounds out
    # the compiler-regression coverage: conv-heavy vs scan-heavy)
    import jax.numpy as jnp
    from bigdl_tpu import nn as _nn
    from bigdl_tpu.models.vgg import vgg16
    from bigdl_tpu.models.rnn import ptb_model

    # r5 config sweep: b128 1385 img/s (0.63 MFU), b256 1392 (0.634),
    # b64 965 (0.44), b128+scoped-vmem-32MiB 1310 — b128/default is the
    # knee; the ~37% over-MXU-floor residual (92 ms vs 58 ms floor,
    # HBM floor 46 ms) is imperfect MXU/DMA overlap on the giant
    # early-layer activations, stable across batch and vmem knobs
    v_batch = 128  # NCHW (the model's native layout; fc head at 7x7)
    rng = np.random.default_rng(2)
    vx = jnp.asarray(rng.normal(0, 1, (v_batch, 3, 224, 224))
                     .astype(np.float32))
    vy = jnp.asarray(rng.integers(0, 1000, (v_batch,)).astype(np.int32))
    emit_guarded(
        "vgg16", "vgg16_images_per_sec_per_chip", v_batch,
        lambda: _measure(vgg16(), v_batch, windows, iters, x=vx, y=vy))

    # PTB "medium" LSTM: vocab 10k, 650x2, seq 35, batch 20 — words/sec.
    # scan_unroll=5, chosen by the r5 sweeps (hoisted input projections
    # active in all rows): unroll 1 < {5, 7} consistently; 5 vs 7 are
    # within each other's spread; full unroll (35) loses loop-invariant
    # hoisting (bytes 1.58→3.32 GB) and regresses.  Pre-optimization
    # baseline (no hoist, no unroll): 31.3k words/s; optimized
    # measurements ranged 145k-280k median across host states.  This
    # number is host-dispatch sensitive (steps are ~3-5 ms): the 4x
    # iters below lengthen windows to ~0.6 s, and the reported spread
    # is the honesty mechanism — judge the number with it.
    p_batch, seq = 20, 35
    px = jnp.asarray(rng.integers(0, 10000, (p_batch, seq))
                     .astype(np.int32))
    py = jnp.asarray(rng.integers(0, 10000, (p_batch, seq))
                     .astype(np.int32))
    emit_guarded(
        "ptb_lstm", "ptb_lstm_words_per_sec_per_chip", p_batch * seq,
        # 4x iters: at ~5 ms/step a 32-iter window is only ~150 ms and
        # host jitter alone produced rel_spread 0.34; ~0.6 s windows
        # put the spread back in the same regime as the conv models.
        # warmup_windows=2: r5 still posted 0.216 rel_spread — the
        # first post-compile windows are the outliers (discard + the
        # trimmed median keep wire/fusion deltas above the noise)
        lambda: _measure(
            ptb_model(10000, 650, 650, 2, scan_unroll=5), p_batch,
            windows, iters * 4, x=px, y=py,
            criterion=_nn.TimeDistributedCriterion(
                _nn.ClassNLLCriterion()),
            units_per_step=p_batch * seq, warmup_windows=2))

    # dispatch-overhead ablation (round-6): the same step, fused via
    # lax.scan at the workload's PRODUCTION_K — the bench mirror of the
    # driver's steps_per_dispatch.  PTB (3-5 ms steps) and Wide&Deep
    # (~9 ms) are the two menu entries whose measured-vs-floor gap and
    # window spread are dominated by host dispatch, not hardware
    # (BENCH_r05: 21.6%/24.0% spread at 0.98/0.64 of floor); the fused
    # numbers quantify exactly that tax.
    emit_guarded(
        "ptb_lstm_fused", "ptb_lstm_fused_words_per_sec_per_chip",
        p_batch * seq,
        lambda: _measure(
            ptb_model(10000, 650, 650, 2, scan_unroll=5), p_batch,
            windows, iters * 4, x=px, y=py,
            criterion=_nn.TimeDistributedCriterion(
                _nn.ClassNLLCriterion()),
            units_per_step=p_batch * seq, fuse_k=PRODUCTION_K["ptb_lstm"],
            warmup_windows=2))

    # Wide&Deep sparse-embedding workload — the remaining BASELINE.json
    # config family (SparseTensor + embedding): COO wide features
    # through SparseLinear/segment-sum + embedding bags + MLP, census-
    # recipe dims at recommender batch.  f32 (lookup/bandwidth-bound;
    # bf16 buys nothing and would perturb the segment sums), so the
    # roofline peak is the v5e f32 matmul rate (~bf16 peak / 4 — moot
    # in practice: this workload's MXU floor is ~0 either way).
    # The 0.2-0.3 hbm_floor_fraction is the wide-table gradient's
    # random scatter (64K updates into 100K slots ≈ 3 ms measured
    # standalone) — a lowering cost the byte model doesn't see, same
    # class as Inception's S&S.  Alternatives measured WORSE on-chip
    # (r5): segment_sum(indices_are_sorted=True) 4.25 vs 3.91 ms on
    # the fwd path; sort+segsum weight-grad 4.29 vs scatter's 3.04 ms.
    # XLA's scatter is the best known formulation; revisit per
    # toolchain bump.
    wd_batch = 8192

    def _wide_deep_measure(fuse_k=None, kernel_impl=None, windows_=None,
                           iters_=None):
        from bigdl_tpu.models.recommender import WideAndDeep
        from bigdl_tpu.nn.sparse import COOBatch
        nnz_per = 8
        wide_dim, fields = 100_000, [10_000, 1_000, 100, 100, 50]
        m = WideAndDeep(wide_dim, fields, dense_dim=13, embed_dim=16,
                        hidden=(100, 50), kernel_impl=kernel_impl)
        r = np.random.default_rng(3)
        nnz = wd_batch * nnz_per
        coo = COOBatch(
            jnp.asarray(np.repeat(np.arange(wd_batch, dtype=np.int32),
                                  nnz_per)),
            jnp.asarray(r.integers(0, wide_dim, nnz).astype(np.int32)),
            jnp.asarray(np.ones(nnz, np.float32)),
            (wd_batch, wide_dim))
        deep_ids = jnp.asarray(np.stack(
            [r.integers(0, c, wd_batch) for c in fields],
            axis=1).astype(np.int32))
        dense = jnp.asarray(r.normal(0, 1, (wd_batch, 13))
                            .astype(np.float32))
        yb = jnp.asarray(r.integers(0, 2, wd_batch).astype(np.float32))

        class _SqueezeBCE:  # model emits (N, 1) logits->sigmoid
            def __init__(self):
                self.bce = _nn.BCECriterion()

            def apply(self, out, y):
                return self.bce.apply(out[:, 0], y)

        # 2x iters: ~9 ms/step needs ~0.6 s windows for a stable
        # median (same rationale as the PTB entry above)
        return _measure(m, wd_batch,
                        windows if windows_ is None else windows_,
                        iters * 2 if iters_ is None else iters_,
                        x=(coo, deep_ids, dense), y=yb,
                        criterion=_SqueezeBCE(),
                        compute_dtype=jnp.float32, fuse_k=fuse_k,
                        warmup_windows=2)

    emit_guarded("wide_deep", "wide_deep_records_per_sec_per_chip",
                 wd_batch, _wide_deep_measure,
                 peak=PEAK_BF16_FLOPS / 4)
    emit_guarded("wide_deep_fused", "wide_deep_fused_records_per_sec_per_chip",
                 wd_batch,
                 lambda: _wide_deep_measure(fuse_k=PRODUCTION_K["wide_deep"]),
                 peak=PEAK_BF16_FLOPS / 4)

    # fused custom kernels (round-10, the HBM-floor PR): the same two
    # memory-wall workloads with the pallas kernels engaged
    # (impl="pallas" — fused VMEM-resident LSTM cell, fused COO
    # embedding-bag; ops/pallas_lstm.py / ops/pallas_embed.py), vs
    # their XLA baselines above.  CPU-host caveat (also recorded in the
    # JSON): off-TPU these run under pallas INTERPRET mode — an XLA
    # emulation of the kernel body — so throughput AND cost-analysis
    # bytes are correctness-only, NOT perf; the strictly-lower
    # bytes/step claim is gated on canned step-program HLO in
    # tests/test_byte_audit.py, and the on-chip capture is carried
    # measurement debt (ROADMAP).  Off-TPU the entries run shortened
    # windows — they exist to record engagement + deltas, not timings.
    kernel_caveat = (
        "cpu-host interpret-mode pallas kernels: correctness-only "
        "numbers, not perf; on-chip bytes/step capture is carried "
        "measurement debt" if _toolchain()["platform"] != "tpu" else None)
    on_tpu = kernel_caveat is None
    k_windows = windows if on_tpu else min(windows, 2)
    k_iters = iters * 4 if on_tpu else max(2, iters // 8)
    emit_guarded(
        "ptb_lstm_fused_cell",
        "ptb_lstm_fused_cell_words_per_sec_per_chip", p_batch * seq,
        lambda: _measure(
            ptb_model(10000, 650, 650, 2, scan_unroll=5,
                      kernel_impl="pallas"), p_batch,
            k_windows, k_iters, x=px, y=py,
            criterion=_nn.TimeDistributedCriterion(
                _nn.ClassNLLCriterion()),
            units_per_step=p_batch * seq, warmup_windows=1))
    emit_guarded(
        "wide_deep_fused_bag",
        "wide_deep_fused_bag_records_per_sec_per_chip", wd_batch,
        lambda: _wide_deep_measure(kernel_impl="pallas",
                                   windows_=k_windows,
                                   iters_=k_iters),
        peak=PEAK_BF16_FLOPS / 4)
    if kernel_caveat:
        out["fused_kernel_caveat"] = kernel_caveat
    # bytes/step + hbm_floor_fraction deltas, XLA baseline vs pallas
    # (from each entry's compiled cost analysis)
    fkb = {}
    for name_, base_p, fused_p in (
            ("ptb_lstm", "ptb_lstm", "ptb_lstm_fused_cell"),
            ("wide_deep", "wide_deep", "wide_deep_fused_bag")):
        bb = out.get(f"{base_p}_bottleneck")
        fb = out.get(f"{fused_p}_bottleneck")
        if bb and fb:
            fkb[name_] = {
                "bytes_per_step_GB_xla": bb["xla_bytes_GB"],
                "bytes_per_step_GB_pallas": fb["xla_bytes_GB"],
                "bytes_delta_GB": round(
                    fb["xla_bytes_GB"] - bb["xla_bytes_GB"], 2),
                "hbm_floor_fraction_xla": bb["hbm_floor_fraction"],
                "hbm_floor_fraction_pallas": fb["hbm_floor_fraction"],
            }
    out["fused_kernel_bytes"] = fkb if fkb else None

    # dispatch_overhead_fraction = 1 - t_fused_step / t_unfused_step,
    # from the TRIMMED window medians when available (negative = fusion
    # lost — also worth knowing; never clamped).  This is the measured
    # per-step host dispatch tax the K-step driver loop removes.
    def _metric(prefix, key):
        spread = out.get(f"{prefix}_spread", {})
        return spread.get("trimmed_median") or out.get(key)

    dof = {}
    for name_, base_k, fused_k in (
            ("ptb_lstm", "ptb_lstm_words_per_sec_per_chip",
             "ptb_lstm_fused_words_per_sec_per_chip"),
            ("wide_deep", "wide_deep_records_per_sec_per_chip",
             "wide_deep_fused_records_per_sec_per_chip")):
        base_v = _metric(name_, base_k)
        fused_v = _metric(f"{name_}_fused", fused_k)
        if base_v and fused_v:
            dof[name_] = round(1.0 - base_v / fused_v, 4)
    out["dispatch_overhead_fraction"] = dof if dof else None
    # dispatch_fuse_k_source (round-11): where each workload's fused-K
    # came from — the autotuned tuned_configs.json entry for this
    # backend, or the hand-maintained round-7 dict the shim falls back
    # to (bench.PRODUCTION_K deprecation shim).
    fuse_src = {w: PRODUCTION_K.source(w)
                for w in ("ptb_lstm", "wide_deep")}
    out["dispatch_fuse_k"] = {w: k for w, (k, _) in fuse_src.items()}
    out["dispatch_fuse_k_source"] = {w: s
                                     for w, (_, s) in fuse_src.items()}

    if not smoke:
        co = _collective_overhead()
        if co is not None:
            out.update(co)
        else:
            out["collective_overhead_fraction"] = None
            out["collective_gate_0p38"] = "FAIL"
            out["collective_error"] = "collective child subprocess failed"
        sc = _scaling_efficiency()
        if sc is not None:
            out["scaling_1v8_informational"] = sc
        else:
            out["scaling_1v8_informational"] = {
                "value": None, "error": "scaling child failed"}
        out["chip_gate"] = _chip_gate()
    out["telemetry"] = bench_reg.scalars()
    print(json.dumps(out))


def scaling_child():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from bigdl_tpu import nn, optim
    from bigdl_tpu.models.resnet import resnet_cifar

    n = int(os.environ["_BENCH_SCALING_N"])
    devs = jax.devices()
    assert len(devs) >= n, (n, devs)
    mesh = Mesh(np.array(devs[:n]), ("data",))

    model = resnet_cifar(depth=20)
    criterion = nn.ClassNLLCriterion()
    method = optim.SGD(learning_rate=0.1, momentum=0.9)
    params, mstate = model.init(jax.random.PRNGKey(0))
    ostate = method.init_state(params)
    batch = 128  # FIXED global batch: same total work for every n
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, 3, 32, 32)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, (batch,)).astype(np.int32))
    data_sh = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())
    x = jax.device_put(x, data_sh)
    y = jax.device_put(y, data_sh)
    params = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), params)
    mstate = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), mstate)
    ostate = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), ostate)

    def loss_fn(p, ms, x, y):
        out, ms2 = model.apply(p, ms, x, training=True)
        return criterion.apply(out, y), ms2

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(p, ms, os_, x, y, it):
        (loss, ms), g = grad_fn(p, ms, x, y)
        p, os_ = method.update(g, p, os_, 0.1, it)
        return p, ms, os_, loss

    # warmup discipline matching the main measurement (VERDICT r4 weak#6)
    for w in range(2):
        params, mstate, ostate, loss = step(params, mstate, ostate, x, y, w)
    loss.block_until_ready()

    # steady-state window filter (telemetry PR; the r05
    # measurement_error fix): every window runs under a tracer span so
    # the capture is auditable, then (a) the first WARM_WINDOWS are
    # excluded as compile/allocator/page-in warmup, (b) remaining
    # windows whose rate deviates >UNSTEADY_TOL from the trimmed median
    # are excluded as unsteady (host jitter, cache effects — on one
    # physical core these produced the physically-impossible r05
    # super-linear "scaling").  The excluded fraction is REPORTED, not
    # hidden: a box that can't produce steady windows shows it.
    from bigdl_tpu.telemetry import Tracer
    WARM_WINDOWS = 2
    tracer = Tracer(enabled=True)
    iters = 10
    for w in range(WARM_WINDOWS + 6):
        t0ns = time.perf_counter_ns()
        for i in range(iters):
            params, mstate, ostate, loss = step(params, mstate, ostate,
                                                x, y, 2 + w * iters + i)
        loss.block_until_ready()
        t1ns = time.perf_counter_ns()
        tracer.record("window", t0ns, t1ns, cat="measure",
                      rate=round(batch * iters / ((t1ns - t0ns) / 1e9),
                                 1),
                      warmup=w < WARM_WINDOWS)
    # decisions read back from the SPANS (the trace is the audit trail)
    spans = [(e[6]["rate"], e[6]["warmup"]) for e in tracer.events()
             if e[1] == "window"]
    steady = [r for r, warm in spans if not warm]
    # excluded_fraction is over the STEADY CANDIDATES only — warmup
    # windows are excluded by design on every run and would put a
    # constant floor under the "couldn't hold steady" signal
    kept, excluded, ref = steady_windows(steady)
    print(json.dumps({
        "ips": statistics.median(kept) if kept else ref,
        "windows_total": len(spans),
        "windows_warmup": len(spans) - len(steady),
        "windows_excluded": excluded,
        "excluded_fraction": round(excluded / max(1, len(steady)), 4),
    }))


def collective_child():
    """Time one sharded DP training step with the gradient all-reduce
    present ("with"), ablated ("ablated" — identical per-device compute,
    gradients simply left unreduced so each device trains locally), with
    3 extra all-reduces ("inject" — the gate's self-test), or through
    the explicit grad_sync protocol ("gs_f32"/"gs_bf16" — bucketed
    reduce-scatter in the wire dtype, owned-slice update, all-gather).
    The model is the framework's own Sequential MLP sized param-heavy
    (module-doc calibration) so the collective is visible above step
    noise.  Prints one JSON line: ``{"ms": <median ms/step>,
    "wire_bytes": <byte_audit per-collective payload>}``."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from bigdl_tpu import nn, optim
    from bigdl_tpu.parallel import grad_sync as gs
    from tools.byte_audit import collective_wire_bytes

    mode = os.environ["_BENCH_COLL_MODE"]
    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs), ("data",))
    n = 8

    D = 2048
    model = (nn.Sequential()
             .add(nn.Linear(D, D)).add(nn.Tanh())
             .add(nn.Linear(D, D)).add(nn.Tanh())
             .add(nn.Linear(D, D)))
    criterion = nn.MSECriterion()
    method = optim.SGD(learning_rate=0.01, momentum=0.9)
    params, mstate = model.init(jax.random.PRNGKey(0))
    batch = 64  # 8/device
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, D)).astype(np.float32))
    y = jnp.asarray(rng.normal(0, 1, (batch, D)).astype(np.float32))

    def loss_fn(p, ms, x, y):
        out, ms2 = model.apply(p, ms, x, training=True)
        return criterion.apply(out, y), ms2

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    psum = lambda t: jax.tree_util.tree_map(
        lambda a: lax.psum(a, "data"), t)

    repl = jax.tree_util.tree_map(lambda _: P(), params)
    replm = jax.tree_util.tree_map(lambda _: P(), mstate)

    if mode.startswith("gs_"):
        wire = {"gs_f32": jnp.float32, "gs_bf16": jnp.bfloat16}[mode]
        from bigdl_tpu.utils.config import get_config
        plan = gs.build_plan(params, n, get_config().grad_bucket_bytes)
        ostate = gs.init_state(plan, params, method)

        def one_step(p, ms, os_, x, y, it):
            (loss, ms2), g = grad_fn(p, ms, x, y)
            p2, os2 = gs.sync_and_update(plan, g, os_, method, 0.1, it,
                                         wire_dtype=wire,
                                         axis_name="data")
            return p2, ms2, os2, loss[None]

        os_spec = jax.tree_util.tree_map(lambda _: P("data"), ostate)
    else:
        ostate = method.init_state(params)

        def one_step(p, ms, os_, x, y, it):
            (loss, ms2), g = grad_fn(p, ms, x, y)
            if mode in ("with", "inject"):
                g = psum(g)
            if mode == "inject":
                g = psum(psum(psum(g)))  # 3 artificial extra all-reduces
            p2, os2 = method.update(g, p, os_, 0.1, it)
            return p2, ms2, os2, loss[None]

        os_spec = jax.tree_util.tree_map(lambda _: P(), ostate)

    # place inputs to match the specs BEFORE lowering: the AOT
    # executable binds the argument shardings it was lowered with
    place = lambda t, spec: jax.tree_util.tree_map(
        lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)), t, spec)
    params = place(params, repl)
    mstate = place(mstate, replm)
    ostate = place(ostate, os_spec)
    x = jax.device_put(x, NamedSharding(mesh, P("data")))
    y = jax.device_put(y, NamedSharding(mesh, P("data")))

    # replication checking off: in "ablated" mode params are
    # legitimately device-varying (that is the point of the ablation)
    fn = jax.jit(gs.shard_map_compat(
        one_step, mesh,
        in_specs=(repl, replm, os_spec, P("data"), P("data"), P()),
        out_specs=(repl, replm, os_spec, P("data"))),
        donate_argnums=(0, 1, 2))
    # AOT compile: the executable serves the timing loop AND exposes
    # the optimized HLO for the bytes-on-wire audit
    compiled = fn.lower(params, mstate, ostate, x, y,
                        np.int32(0)).compile()
    try:
        wire_bytes = collective_wire_bytes(compiled.as_text())
    except Exception as e:  # audit is best-effort; timing must survive
        wire_bytes = {"error": f"{type(e).__name__}: {e}"}
    for i in range(3):  # warmup
        params, mstate, ostate, loss = compiled(params, mstate, ostate,
                                                x, y, np.int32(i))
    loss.block_until_ready()
    meds = []
    for w in range(3):
        iters = 5
        t0 = time.perf_counter()
        for i in range(iters):
            params, mstate, ostate, loss = compiled(
                params, mstate, ostate, x, y, np.int32(3 + w * iters + i))
        loss.block_until_ready()
        meds.append((time.perf_counter() - t0) / iters * 1e3)
    print(json.dumps({"ms": statistics.median(meds),
                      "wire_bytes": wire_bytes}))


def serving_bench(smoke: bool = False):
    """Offered-load sweep over the ``bigdl_tpu.serving`` engine.

    Closed-loop load: T caller threads each issue single-row blocking
    ``predict`` calls back-to-back (the worst coalescing case — every
    request is 1 row, so occupancy is earned purely by the batcher).
    Per load point: rows/sec, p50/p95/p99 latency, mean batch occupancy,
    and dispatches-per-request (1/T is perfect coalescing at T ≤
    max_batch).  A fresh service per point keeps stats windows clean;
    warmup (AOT bucket compiles) happens before the timed window, and
    any steady-state compile is RECORDED as a gate failure — per-point
    ``recompile_gate: FAIL`` plus top-level
    ``serving_recompile_gate: FAIL`` — following the bench's
    record-never-abort discipline (same shape as
    ``collective_gate_0p38``); the hard assertion lives in
    ``tests/test_serving.py``.
    """
    import threading as _threading

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.serving import InferenceService

    din, n_threads_sweep = 64, (1, 4, 16, 64)
    per_thread = 25 if smoke else 200
    model = nn.Sequential(
        nn.Linear(din, 256), nn.ReLU(), nn.Linear(256, 256), nn.ReLU(),
        nn.Linear(256, 8), nn.SoftMax())
    model.initialize(rng=0)
    spec = ((din,), np.float32)
    rng = np.random.default_rng(0)

    out = {"metric": "serving_throughput_rows_per_sec",
           "unit": "rows/sec", "toolchain": _toolchain(),
           "config": f"mlp{din}x256x256x8/max_batch32/timeout2ms/"
                     f"single-row-closed-loop", "sweep": []}
    best = 0.0
    for n_threads in n_threads_sweep:
        svc = InferenceService(model, input_spec=spec, max_batch_size=32,
                               batch_timeout_ms=2.0, queue_capacity=4096,
                               name=f"bench-load{n_threads}")
        warm_compiles = svc.compile_count
        xs = [rng.normal(0, 1, (1, din)).astype(np.float32)
              for _ in range(n_threads)]
        barrier = _threading.Barrier(n_threads + 1)
        errs = []

        def worker(x):
            barrier.wait()
            try:
                for _ in range(per_thread):
                    svc.predict(x, timeout=120)
            except Exception as e:  # recorded, never dropped
                errs.append(f"{type(e).__name__}: {e}")

        threads = [_threading.Thread(target=worker, args=(x,))
                   for x in xs]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        stats = svc.stats()
        svc.stop()
        n_req = n_threads * per_thread
        point = {
            "offered_threads": n_threads,
            "requests": n_req,
            "throughput_rps": round(n_req / wall, 1),
            "latency_ms": stats["latency_ms"],
            # per-row-bucket latency windows (ROADMAP 1c): which bucket
            # pays the p99 — a 1-row dispatch and a 32-row bucket have
            # very different service times the global window hides
            "latency_ms_by_bucket": stats["latency_ms_by_bucket"],
            "mean_batch_occupancy": stats["mean_batch_occupancy"],
            "dispatch_count": stats["dispatch_count"],
            "dispatches_per_request":
                round(stats["dispatch_count"] / n_req, 4),
            "steady_state_compiles": svc.compile_count - warm_compiles,
            # end-of-run registry snapshot (telemetry round 2): the
            # capture carries the numbers a /metrics scrape would have
            # seen, so bench output and the admin plane agree by
            # construction
            "telemetry": svc.metrics.registry.scalars(),
        }
        if errs:
            point["errors"] = errs[:3]
        if svc.compile_count != warm_compiles:
            point["recompile_gate"] = "FAIL"  # GL106-for-serving tripped
        out["sweep"].append(point)
        best = max(best, point["throughput_rps"])
    out["value"] = best
    out["serving_recompile_gate"] = (
        "FAIL" if any(p.get("recompile_gate") == "FAIL"
                      for p in out["sweep"]) else "PASS")
    from bigdl_tpu.serving import row_buckets
    out["serving_buckets"] = list(row_buckets(32))
    # admin-plane scrape overhead: the SAME closed-loop load twice — once
    # with a 1 Hz /metrics scraper hitting a live AdminServer, once
    # without — so the exporter's cost on tail latency is a measured
    # number, not a claim.  Rendering runs on the scraper's thread; the
    # expected delta is ~0 (the hot path never touches the admin plane),
    # and any real regression shows up as p99_scraped - p99_baseline.
    out["admin_scrape_overhead"] = _admin_scrape_overhead(
        model, spec, rng, smoke)
    # wire mode (ISSUE 14): the SAME model behind the HTTP frontend vs
    # in-process submit → wire_overhead_ms, plus the zero-dropped-
    # requests gate through 3 hot deploys under sustained wire load
    out["wire"] = _wire_bench(model, spec, rng, smoke)
    out["wire_zero_drop_gate"] = out["wire"]["zero_drop_gate"]
    # connection-scalability sweep (ISSUE 19): idle flood + active mix
    # on the event-loop core vs the threaded baseline
    out["connection_sweep"] = _connection_sweep(model, spec, rng, smoke)
    # int8 quantized speed path (the int8 serving PR): the SAME model
    # served f32 / bf16-params / int8-quantized (kernel-backed,
    # ops/pallas_int8_gemm.py) under the same closed-loop load —
    # throughput, p50/p99, occupancy, bytes/step from compiled cost
    # analysis, and the quantized_speedup ratio
    out["quantized"] = _quantized_serving_bench(model, spec, rng, smoke)
    out["quantized_speedup"] = out["quantized"].get("quantized_speedup")
    if out["quantized"].get("caveat"):
        out["quantized_kernel_caveat"] = out["quantized"]["caveat"]
    # continuous-batching decode column (ISSUE 20): mixed-length
    # autoregressive generate sweep through a DecodeService —
    # tokens/sec, TTFT, inter-token latency, batch occupancy — vs the
    # static-batch (wave-barriered) baseline schedule
    out["decode"] = _decode_serving_bench(smoke)
    out["decode_continuous_vs_static_speedup"] = out["decode"].get(
        "continuous_vs_static_speedup")
    if out["decode"].get("caveat"):
        out["decode_cpu_caveat"] = out["decode"]["caveat"]
    return out


def _wire_bench(model, spec, rng, smoke: bool) -> dict:
    """Loopback closed-loop HTTP clients vs in-process predicts on the
    same deployed model.  Reports client-side p50/p99 for both paths
    and their delta (``wire_overhead_ms`` — the HTTP hop: JSON
    round-trip, admission, dispatch).  TCP connect/handshake is timed
    EXPLICITLY per connection and reported as ``connect_latency_ms``
    instead of letting http.client's lazy connect fold it into the
    first request's latency (the ISSUE-19 sweep fix — handshake cost
    scales with accept-path pressure, per-request cost with dispatch
    pressure; mixing them hid both).  Then holds the offered load
    while 3 :class:`~bigdl_tpu.frontend.HotCutover` deploys run;
    every wire request must come back 200 with the bitwise-expected
    output (every version serves the same params, so correctness is
    exact).  Record-never-abort: the gate FAILs in the capture, the
    hard assert lives in ``tests/test_frontend.py``."""
    import http.client
    import threading as _threading

    import numpy as np

    from bigdl_tpu.frontend import FrontendServer, HotCutover
    from bigdl_tpu.serving import ModelRegistry

    n_threads = 4 if smoke else 8
    per_thread = 25 if smoke else 100
    din = spec[0][0]

    reg = ModelRegistry()
    svc = reg.deploy("wire", model, input_spec=spec, max_batch_size=32,
                     batch_timeout_ms=2.0, queue_capacity=4096)
    fe = FrontendServer(reg, port=0)
    fe.start()
    xs = [rng.normal(0, 1, (1, din)).astype(np.float32)
          for _ in range(n_threads)]
    expected = [np.asarray(model.apply(svc.params, svc.state, x,
                                       training=False)[0])
                for x in xs]

    def wire_load(tag, deploys=0):
        """Closed-loop wire clients (one keep-alive connection per
        thread); optionally run hot deploys from the main thread while
        the load holds.  Returns (lat_ms list, connect_ms list, bad
        list, reports)."""
        lats, conn_lats, bad = [], [], []
        barrier = _threading.Barrier(n_threads + 1)
        bodies = [json.dumps({"inputs": x.tolist()}).encode()
                  for x in xs]

        def worker(t):
            conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                              timeout=120)
            barrier.wait()
            my_lats = []
            try:
                # explicit timed connect: handshake cost reported on
                # its own, never folded into request latency
                t0 = time.perf_counter()
                conn.connect()
                conn_lats.append((time.perf_counter() - t0) * 1e3)
                for _ in range(per_thread):
                    t0 = time.perf_counter()
                    conn.request("POST", "/v1/models/wire/predict",
                                 body=bodies[t],
                                 headers={"Content-Type":
                                          "application/json"})
                    resp = conn.getresponse()
                    payload = resp.read()
                    my_lats.append((time.perf_counter() - t0) * 1e3)
                    if resp.status != 200:
                        bad.append(f"{tag}: HTTP {resp.status}")
                        continue
                    got = np.asarray(
                        json.loads(payload)["outputs"], np.float32)
                    # allclose, not bitwise: a wire request coalesces
                    # into whatever row bucket the moment offers, and
                    # bucket executables differ in fusion order by a
                    # last ulp (the documented resilience-bench
                    # concession; the BITWISE wire gate at fixed
                    # bucket lives in tests/test_frontend.py)
                    if not np.allclose(got, expected[t],
                                       rtol=1e-5, atol=1e-7):
                        bad.append(f"{tag}: wrong output thread {t}")
            except Exception as e:
                bad.append(f"{tag}: {type(e).__name__}: {e}")
            finally:
                conn.close()
            lats.extend(my_lats)

        threads = [_threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for th in threads:
            th.start()
        barrier.wait()
        reports = []
        if deploys:
            cut = HotCutover(reg, fe)
            try:
                for _ in range(deploys):
                    reports.append(cut.deploy(
                        "wire", model, max_batch_size=32,
                        batch_timeout_ms=2.0, queue_capacity=4096))
            except Exception as e:
                # recorded (fails the gate), never aborts — and the
                # worker threads below still get joined
                bad.append(f"{tag}: deploy failed: "
                           f"{type(e).__name__}: {e}")
        for th in threads:
            th.join()
        return lats, conn_lats, bad, reports

    def inproc_load():
        lats = []
        barrier = _threading.Barrier(n_threads + 1)

        def worker(t):
            barrier.wait()
            my_lats = []
            for _ in range(per_thread):
                t0 = time.perf_counter()
                reg.predict("wire", xs[t], timeout=120)
                my_lats.append((time.perf_counter() - t0) * 1e3)
            lats.extend(my_lats)

        threads = [_threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for th in threads:
            th.start()
        barrier.wait()
        for th in threads:
            th.join()
        return lats

    def pct(samples, q):
        s = sorted(samples)
        return round(s[min(len(s) - 1,
                           max(0, int(round(q * len(s))) - 1))], 3)

    # discarded warmup (first-run jit/socket/thread-pool costs), then
    # the measured pair on warm state.  Record-never-abort: a cutover
    # drain timeout (slow/loaded host) or any phase error lands in the
    # gate as FAIL — it must not kill the whole serving bench nor leak
    # the frontend/registry into later sections
    bad, reports = [], []
    wire_lat = inproc_lat = cut_lat = wire_conn = [0.0]
    try:
        wire_load("warmup")
        inproc_load()
        wire_lat, wire_conn, wire_bad, _ = wire_load("steady")
        inproc_lat = inproc_load()
        # 3 hot deploys under sustained wire load: the zero-drop gate
        cut_lat, _cut_conn, cut_bad, reports = wire_load("cutover",
                                                         deploys=3)
        bad = wire_bad + cut_bad
    except Exception as e:
        bad.append(f"wire bench phase error: {type(e).__name__}: {e}")
    out = {
        "offered_threads": n_threads,
        "requests_per_phase": n_threads * per_thread,
        "wire_latency_ms": {"p50": pct(wire_lat, 0.50),
                            "p99": pct(wire_lat, 0.99)},
        "connect_latency_ms": {"p50": pct(wire_conn, 0.50),
                               "p99": pct(wire_conn, 0.99)},
        "inproc_latency_ms": {"p50": pct(inproc_lat, 0.50),
                              "p99": pct(inproc_lat, 0.99)},
        "wire_overhead_ms": {
            "p50": round(pct(wire_lat, 0.50) - pct(inproc_lat, 0.50), 3),
            "p99": round(pct(wire_lat, 0.99) - pct(inproc_lat, 0.99), 3)},
        "cutover_latency_ms": {"p50": pct(cut_lat, 0.50),
                               "p99": pct(cut_lat, 0.99)},
        "hot_deploys": len(reports),
        "cutovers": [{k: r[k] for k in ("old_version", "new_version",
                                        "warmup_s", "wire_drain_s")}
                     for r in reports],
        "bad_responses": len(bad),
        "zero_drop_gate": "PASS" if not bad else "FAIL",
        "frontend_telemetry": fe.metrics.scalars(),
    }
    if bad:
        out["errors"] = bad[:5]
    fe.stop()
    reg.stop_all()
    return out


# idle-connection holder, run as a SUBPROCESS: N parked sockets in
# this process would double-bill the fd budget (server side + client
# side), capping the sweep at half the rlimit.  Prints "READY <open>
# <errors>" once all connects resolve, holds until stdin closes.
_IDLE_CHILD_SRC = r"""
import socket, sys, time
port, n = int(sys.argv[1]), int(sys.argv[2])
socks, errs = [], 0
for i in range(n):
    try:
        socks.append(socket.create_connection(("127.0.0.1", port),
                                              timeout=60))
    except OSError:
        errs += 1
    if i % 512 == 511:
        time.sleep(0.05)  # let the accept loop drain the backlog
sys.stdout.write("READY %d %d\n" % (len(socks), errs))
sys.stdout.flush()
sys.stdin.readline()
for s in socks:
    try:
        s.close()
    except OSError:
        pass
"""


def _connection_sweep(model, spec, rng, smoke: bool) -> dict:
    """Connection-count scalability sweep (ISSUE 19, ROADMAP item 2):
    park N idle keep-alive connections on the frontend, then run a
    closed-loop active mix through them and record p50/p99, connect
    latency, throughput and the server's own open-connection count.
    The event-loop core sweeps to 10k idle; the threaded baseline
    stops at 1k (a 10k-thread point would measure the OS scheduler,
    not the wire plane — and that asymmetry IS the result).

    Record-never-abort: any point that fails (EMFILE, connect
    timeout, refused) records an ``error`` field and the sweep moves
    on to the next point."""
    import http.client
    import subprocess
    import sys as _sys
    import threading as _threading

    import numpy as np

    from bigdl_tpu.frontend import FrontendServer
    from bigdl_tpu.serving import ModelRegistry

    din = spec[0][0]
    n_threads = 4 if smoke else 8
    per_thread = 10 if smoke else 50
    points = ([("eventloop", 0), ("eventloop", 200),
               ("threaded", 0), ("threaded", 200)] if smoke else
              [("eventloop", 0), ("eventloop", 1000),
               ("eventloop", 10000),
               ("threaded", 0), ("threaded", 1000)])
    xs = [rng.normal(0, 1, (1, din)).astype(np.float32)
          for _ in range(n_threads)]
    bodies = [json.dumps({"inputs": x.tolist()}).encode() for x in xs]

    def pct(samples, q):
        s = sorted(samples) or [0.0]
        return round(s[min(len(s) - 1,
                           max(0, int(round(q * len(s))) - 1))], 3)

    def active_mix(port):
        """One closed-loop burst; returns (lats, connect_ms, bad,
        wall_s)."""
        lats, conn_ms, bad = [], [], []
        barrier = _threading.Barrier(n_threads + 1)

        def worker(t):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=120)
            barrier.wait()
            my = []
            try:
                t0 = time.perf_counter()
                conn.connect()
                conn_ms.append((time.perf_counter() - t0) * 1e3)
                for _ in range(per_thread):
                    t0 = time.perf_counter()
                    conn.request("POST", "/v1/models/wire/predict",
                                 body=bodies[t],
                                 headers={"Content-Type":
                                          "application/json"})
                    resp = conn.getresponse()
                    resp.read()
                    my.append((time.perf_counter() - t0) * 1e3)
                    if resp.status != 200:
                        bad.append(f"HTTP {resp.status}")
            except Exception as e:
                bad.append(f"{type(e).__name__}: {e}")
            finally:
                conn.close()
            lats.extend(my)

        threads = [_threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for th in threads:
            th.start()
        t_wall = time.perf_counter()
        barrier.wait()
        for th in threads:
            th.join()
        return lats, conn_ms, bad, time.perf_counter() - t_wall

    out = {"idle_holder": "subprocess",
           "active_threads": n_threads,
           "requests_per_point": n_threads * per_thread,
           "points": []}
    for core, idle in points:
        point = {"core": core, "idle_target": idle}
        reg = fe = child = None
        try:
            reg = ModelRegistry()
            reg.deploy("wire", model, input_spec=spec,
                       max_batch_size=32, batch_timeout_ms=2.0,
                       queue_capacity=4096)
            # uncapped + no reaper: the sweep measures coexistence
            # with the idle flood, not the cap refusing it
            fe = FrontendServer(reg, port=0, core=core,
                                max_connections=0, idle_timeout_s=0.0)
            fe.start()
            if idle:
                child = subprocess.Popen(
                    [_sys.executable, "-c", _IDLE_CHILD_SRC,
                     str(fe.port), str(idle)],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    text=True)
                ready = (child.stdout.readline() or "").split()
                opened = int(ready[1]) if ready[:1] == ["READY"] else 0
                point["idle_open"] = opened
                point["idle_connect_errors"] = (
                    int(ready[2]) if len(ready) > 2 else idle - opened)
                deadline = time.monotonic() + 120
                while (fe.open_connections < opened
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
            active_mix(fe.port)  # warmup (jit + thread pools)
            lats, conn_ms, bad, wall = active_mix(fe.port)
            point.update({
                "open_connections": fe.open_connections,
                "latency_ms": {"p50": pct(lats, 0.50),
                               "p99": pct(lats, 0.99)},
                "connect_ms": {"p50": pct(conn_ms, 0.50),
                               "p99": pct(conn_ms, 0.99)},
                "throughput_rps": (round(len(lats) / wall, 1)
                                   if wall > 0 else 0.0),
                "bad_responses": len(bad),
            })
            if bad:
                point["errors"] = bad[:3]
        except Exception as e:
            point["error"] = f"{type(e).__name__}: {e}"
        finally:
            if child is not None:
                try:
                    child.stdin.write("\n")
                    child.stdin.flush()
                    child.wait(timeout=60)
                except Exception:
                    child.kill()
            if fe is not None:
                try:
                    fe.stop()
                except Exception:
                    pass
            if reg is not None:
                try:
                    reg.stop_all()
                except Exception:
                    pass
        out["points"].append(point)
    sustained = [p.get("idle_open", 0) for p in out["points"]
                 if p["core"] == "eventloop" and "error" not in p
                 and p.get("bad_responses", 1) == 0]
    out["max_idle_sustained_eventloop"] = max(sustained, default=0)
    return out


def _quantized_serving_bench(model, spec, rng, smoke: bool) -> dict:
    """int8-vs-bf16-vs-f32 serving column (the int8 speed-path PR).

    The SAME bench MLP behind three :class:`InferenceService` variants:
    f32 params (baseline), params cast to bf16, and the int8-quantized
    twin (``nn.quantized.quantize``, weight-only mode, ``impl="pallas"``
    so the ops/pallas_int8_gemm.py path engages — only its
    supported() shapes, here the aligned 256x256 middle layer; the odd
    edge layers take the bitwise XLA fallback, which is the realistic
    mixed deployment).  Per variant: closed-loop throughput_rps,
    p50/p99, mean occupancy, the service's ``weights_dtype`` tag, and
    bytes/step from the compiled fixed-batch forward's cost analysis.
    ``quantized_speedup`` = int8 rps / f32 rps.

    Record-never-abort: any variant failure is captured in its entry.
    CPU-host caveat (recorded like ``fused_kernel_caveat``): off-TPU
    the int8 kernel runs under pallas INTERPRET mode, so throughput
    and cost-analysis bytes are correctness-only, NOT perf — the
    strictly-lower-bytes weight-panel claim is gated on canned HLO in
    ``tests/test_byte_audit.py``, and the load is shortened to
    engagement-proof size.
    """
    import threading as _threading

    import numpy as np

    import jax
    import jax.numpy as jnp
    from bigdl_tpu.nn.quantized import quantize as _quantize
    from bigdl_tpu.serving import InferenceService

    din = spec[0][0]
    on_tpu = _toolchain()["platform"] == "tpu"
    caveat = None if on_tpu else (
        "cpu-host interpret-mode int8 kernel: throughput and "
        "cost-analysis bytes are correctness-only, not perf; "
        "shortened load")
    n_threads = (4 if smoke else 8) if on_tpu else 2
    per_thread = (25 if smoke else 100) if on_tpu else 10

    model._ensure_init()
    bf16_params = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if a.dtype == jnp.float32 else a, model._params)
    try:
        qmodel = _quantize(model, mode="weight_only", impl="pallas")
    except Exception as e:  # recorded below per-variant, never aborts
        qmodel, q_err = None, f"{type(e).__name__}: {e}"
    else:
        q_err = None

    variants = [
        ("f32", model, model._params, model._state),
        ("bf16", model, bf16_params, model._state),
        ("int8", qmodel, None, None),
    ]
    out = {"int8_mode": "weight_only", "caveat": caveat,
           "offered_threads": n_threads,
           "requests_per_variant": n_threads * per_thread}

    def _bytes_per_step(m_, params, state):
        """Compiled cost-analysis bytes of one fixed 32-row forward."""
        xb = jnp.asarray(rng.normal(0, 1, (32, din)).astype(np.float32))

        def fwd(p, s, a):
            return m_.apply(p, s, a, training=False)[0]

        compiled = jax.jit(fwd).lower(params, state, xb).compile()
        c = compiled.cost_analysis()
        if isinstance(c, list):
            c = c[0]
        return float(c.get("bytes accessed", 0.0))

    for tag, m_, p_, s_ in variants:
        entry = {}
        try:
            if m_ is None:
                raise RuntimeError(q_err or "quantize failed")
            svc = InferenceService(m_, p_, s_, input_spec=spec,
                                   max_batch_size=32,
                                   batch_timeout_ms=2.0,
                                   queue_capacity=4096,
                                   name=f"bench-q-{tag}")
            try:
                xs = [rng.normal(0, 1, (1, din)).astype(np.float32)
                      for _ in range(n_threads)]
                barrier = _threading.Barrier(n_threads + 1)
                errs = []

                def worker(x):
                    barrier.wait()
                    try:
                        for _ in range(per_thread):
                            svc.predict(x, timeout=120)
                    except Exception as e:  # recorded, never dropped
                        errs.append(f"{type(e).__name__}: {e}")

                threads = [_threading.Thread(target=worker, args=(x,))
                           for x in xs]
                for t in threads:
                    t.start()
                barrier.wait()
                t0 = time.perf_counter()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                stats = svc.stats()
                lat = stats["latency_ms"] or {}
                entry = {
                    "throughput_rps": round(
                        n_threads * per_thread / wall, 1),
                    "latency_ms": {"p50": lat.get("p50"),
                                   "p99": lat.get("p99")},
                    "mean_batch_occupancy":
                        stats["mean_batch_occupancy"],
                    "weights_dtype": stats.get("weights_dtype", "f32"),
                }
                if errs:
                    entry["errors"] = errs[:3]
            finally:
                svc.stop()
            # params/state as the SERVICE resolved them (the quantized
            # twin re-owns its buffers; init gave empty params)
            entry["bytes_per_step"] = _bytes_per_step(
                m_, svc.params, svc.state)
        except Exception as e:  # record-never-abort
            entry["error"] = f"{type(e).__name__}: {e}"
        out[tag] = entry

    f32_rps = out.get("f32", {}).get("throughput_rps")
    int8_rps = out.get("int8", {}).get("throughput_rps")
    out["quantized_speedup"] = (round(int8_rps / f32_rps, 3)
                                if f32_rps and int8_rps else None)
    fb = out.get("f32", {}).get("bytes_per_step")
    ib = out.get("int8", {}).get("bytes_per_step")
    out["bytes_per_step_ratio_int8_vs_f32"] = (
        round(ib / fb, 3) if fb and ib else None)
    return out


def _decode_serving_bench(smoke: bool) -> dict:
    """Continuous-batching autoregressive decode column (ISSUE 20).

    Offered-load sweep of mixed-length generate requests through ONE
    :class:`DecodeService` (a 2-layer toy LM; the service AOT-compiles
    its step + prefill executables once, before any timed window).
    Closed-loop clients call ``submit(..., on_token=...)`` so TTFT
    (submit → first token) and inter-token gaps are measured at the
    CALLER, per request.  Per load point: tokens/sec, TTFT p50/p99,
    inter-token p50/p99, and window batch occupancy computed from
    stats deltas (step-tokens over slot-steps — admission-emitted
    first tokens excluded, they aren't step work).

    ``static_batch`` is the baseline column: the SAME request mix
    submitted in synchronized waves of ``slots`` requests, each wave
    barriered on its slowest sequence before the next is offered —
    exactly what batch-level (non-iteration-level) scheduling does to
    a decode fleet.  ``continuous_vs_static_speedup`` = continuous
    tokens/sec at matched offered load / static tokens/sec.

    Record-never-abort: any failure lands in the capture as
    ``error``.  CPU-host caveat (recorded like
    ``quantized_kernel_caveat``): off-TPU the per-step dispatch
    overhead of a toy LM dominates, so absolute tokens/sec and the
    speedup ratio are schedule-shape evidence, not TPU perf.
    """
    import threading as _threading

    import numpy as np

    from bigdl_tpu.models.transformer import transformer_lm
    from bigdl_tpu.serving import DecodeService

    on_tpu = _toolchain()["platform"] == "tpu"
    caveat = None if on_tpu else (
        "cpu-host decode: per-step dispatch overhead dominates a "
        "2-layer toy LM, so tokens/sec and the continuous-vs-static "
        "ratio are schedule-shape evidence, not TPU perf; "
        "shortened load")
    slots = 4
    max_new = 4 if smoke else 8
    per_client = 2 if smoke else 6
    lens = (2, 4, 6, 9, 12)
    out = {"unit": "tokens/sec", "slots": slots,
           "max_new_tokens": max_new, "prompt_lens": list(lens),
           "caveat": caveat, "sweep": []}
    try:
        model = transformer_lm(vocab_size=64, embed_dim=32,
                               num_heads=4, num_layers=2,
                               max_len=64).initialize(0)
        dec = DecodeService(model, slots=slots, max_seq_len=32,
                            max_prompt_len=12, prefill_buckets="top",
                            queue_capacity=4096, name="bench-decode")
    except Exception as e:  # record-never-abort
        out["error"] = f"{type(e).__name__}: {e}"
        return out
    rng = np.random.default_rng(7)

    def mk_prompts(n):
        return [rng.integers(1, 64,
                             size=lens[i % len(lens)]).tolist()
                for i in range(n)]

    def snap():
        d = dec.stats()["decode"]
        return (d["steps"], d["tokens_generated"], d["admissions"])

    def run_requests(prompts, ttfts, gaps, errs, lock):
        """Closed loop over ``prompts`` on the calling thread."""
        for p in prompts:
            marks = []
            t0 = time.perf_counter()
            fut = dec.submit(p, max_new_tokens=max_new,
                             on_token=lambda i, t, m=marks:
                                 m.append(time.perf_counter()))
            try:
                fut.result(timeout=300)
            except Exception as e:  # recorded, never dropped
                with lock:
                    errs.append(f"{type(e).__name__}: {e}")
                continue
            with lock:
                if marks:
                    ttfts.append((marks[0] - t0) * 1e3)
                    gaps.extend((b - a) * 1e3 for a, b in
                                zip(marks, marks[1:]))

    def pcts(xs):
        if not xs:
            return None
        a = np.asarray(xs)
        return {"p50": round(float(np.percentile(a, 50)), 3),
                "p99": round(float(np.percentile(a, 99)), 3)}

    def window(steps0, tok0, adm0):
        steps1, tok1, adm1 = snap()
        dsteps = steps1 - steps0
        step_tokens = (tok1 - tok0) - (adm1 - adm0)
        occ = (round(step_tokens / (dsteps * slots), 4)
               if dsteps else None)
        return (tok1 - tok0), occ

    try:
        # warm pass: first-token + step executables already AOT-compile
        # in the ctor, but run one request end-to-end so the timed
        # windows never see a cold scheduler thread
        dec.generate(mk_prompts(1)[0], max_new_tokens=2)

        cont_tps_at = {}
        for n_clients in (2, 8):
            point = {"offered_clients": n_clients,
                     "requests": n_clients * per_client}
            try:
                ttfts, gaps, errs = [], [], []
                lock = _threading.Lock()
                client_prompts = [mk_prompts(per_client)
                                  for _ in range(n_clients)]
                barrier = _threading.Barrier(n_clients + 1)

                def worker(ps):
                    barrier.wait()
                    run_requests(ps, ttfts, gaps, errs, lock)

                threads = [_threading.Thread(target=worker, args=(ps,))
                           for ps in client_prompts]
                for t in threads:
                    t.start()
                s0 = snap()
                barrier.wait()
                t0 = time.perf_counter()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                toks, occ = window(*s0)
                point.update({
                    "tokens_per_sec": round(toks / wall, 1),
                    "ttft_ms": pcts(ttfts),
                    "inter_token_ms": pcts(gaps),
                    "batch_occupancy": occ,
                })
                if errs:
                    point["errors"] = errs[:3]
                cont_tps_at[n_clients] = point["tokens_per_sec"]
            except Exception as e:  # record-never-abort
                point["error"] = f"{type(e).__name__}: {e}"
            out["sweep"].append(point)

        # static-batch baseline: waves of `slots` requests, every wave
        # barriered on its slowest sequence (offered load matches the
        # slots-saturating sweep point: 8 clients over 4 slots offers
        # a full wave the moment the previous one clears)
        static = {"wave_size": slots}
        try:
            n_waves = max(1, (8 * per_client) // slots)
            ttfts, gaps, errs = [], [], []
            lock = _threading.Lock()
            waves = [mk_prompts(slots) for _ in range(n_waves)]
            static["requests"] = n_waves * slots
            s0 = snap()
            t0 = time.perf_counter()
            for wave in waves:
                ws = [_threading.Thread(
                    target=run_requests,
                    args=([p], ttfts, gaps, errs, lock))
                    for p in wave]
                for t in ws:
                    t.start()
                for t in ws:
                    t.join()  # the wave barrier: slowest gates all
            wall = time.perf_counter() - t0
            toks, occ = window(*s0)
            static.update({
                "tokens_per_sec": round(toks / wall, 1),
                "ttft_ms": pcts(ttfts),
                "inter_token_ms": pcts(gaps),
                "batch_occupancy": occ,
            })
            if errs:
                static["errors"] = errs[:3]
        except Exception as e:  # record-never-abort
            static["error"] = f"{type(e).__name__}: {e}"
        out["static_batch"] = static

        st = dec.stats()["decode"]
        out["step_ms_ewma"] = st["step_ms_ewma"]
        out["cumulative_step_occupancy"] = st["step_occupancy"]
        c_tps = cont_tps_at.get(8)
        s_tps = static.get("tokens_per_sec")
        out["continuous_vs_static_speedup"] = (
            round(c_tps / s_tps, 3) if c_tps and s_tps else None)
    except Exception as e:  # record-never-abort
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        try:
            dec.stop(drain=False, timeout=5)
        except Exception:
            pass
    return out


def _admin_scrape_overhead(model, spec, rng, smoke: bool) -> dict:
    import threading as _threading
    import urllib.request

    import numpy as np

    from bigdl_tpu.serving import InferenceService
    from bigdl_tpu.telemetry.admin import AdminServer

    n_threads = 4 if smoke else 16
    per_thread = 25 if smoke else 150
    din = spec[0][0]

    def run_load(scrape: bool):
        svc = InferenceService(
            model, input_spec=spec, max_batch_size=32,
            batch_timeout_ms=2.0, queue_capacity=4096,
            name=f"bench-scrape-{'on' if scrape else 'off'}")
        srv = None
        stop = _threading.Event()
        scrapes = [0]
        if scrape:
            srv = AdminServer(port=0)
            srv.add_registry(svc.name, svc.metrics.registry)
            srv.start()

            def scraper():
                while not stop.is_set():
                    try:
                        urllib.request.urlopen(
                            srv.url("/metrics"), timeout=5).read()
                        scrapes[0] += 1
                    except Exception:
                        pass  # recorded via scrape count staying low
                    stop.wait(1.0)  # the 1 Hz cadence

            _threading.Thread(target=scraper, daemon=True).start()
        xs = [rng.normal(0, 1, (1, din)).astype(np.float32)
              for _ in range(n_threads)]
        barrier = _threading.Barrier(n_threads + 1)

        def worker(x):
            barrier.wait()
            for _ in range(per_thread):
                svc.predict(x, timeout=120)

        threads = [_threading.Thread(target=worker, args=(x,))
                   for x in xs]
        for t in threads:
            t.start()
        barrier.wait()
        for t in threads:
            t.join()
        stop.set()
        stats = svc.stats()
        if srv is not None:
            srv.stop()
        svc.stop()
        return stats["latency_ms"], scrapes[0]

    # discarded warmup load: the FIRST run in the process pays jit/
    # allocator/thread-pool warmup; without it the baseline-then-
    # scraped order would bias the delta toward understating the
    # scrape cost (the scraped run would inherit warm state)
    run_load(scrape=False)
    base_lat, _ = run_load(scrape=False)
    scraped_lat, n_scrapes = run_load(scrape=True)
    return {
        "offered_threads": n_threads,
        "requests": n_threads * per_thread,
        "scrapes": n_scrapes,
        "p99_ms_baseline": base_lat["p99"] if base_lat else None,
        "p99_ms_scraped": scraped_lat["p99"] if scraped_lat else None,
        "p99_overhead_ms": (
            round(scraped_lat["p99"] - base_lat["p99"], 3)
            if base_lat and scraped_lat else None),
    }


def resilience_bench(smoke: bool = False):
    """Availability under replica failure (``--resilience``): the
    ``--serving`` offered-load shape pointed at a 4-replica
    :class:`~bigdl_tpu.resilience.ReplicaSet` while a seeded fault plan
    kills one replica's batcher thread mid-sweep.

    Per load point the capture records the full degradation story:
    requests accounted one-by-one (ok / shed / deadline / error — an
    accepted request that never resolves would show up as a hang and
    fail the ``lost`` gate), wrong-answer count against a precomputed
    expected output (must be 0 — a failover must never fabricate rows;
    compared with allclose because a request may coalesce into any row
    bucket and bucket executables differ in fusion order by a last-ulp
    — the same concession ``test_serving.py`` makes across dispatch
    sizes; the bitwise gate at fixed bucket lives in
    ``tests/test_resilience.py``),
    throughput and p99 split into baseline / degraded (quarantine
    window) / recovered phases from a health-state monitor thread, and
    the ``resilience/*`` counters (death, quarantine, failovers,
    revival, probes, readmission) straight from the registry.  The
    acceptance shape — throughput degrades to ~(N-1)/N rather than
    zero and the replica re-admits after probation — is gated hard in
    ``tests/test_resilience.py``; this entry records the measured
    numbers (record-never-abort) so availability joins the bench
    trajectory.
    """
    import threading as _threading

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.resilience import ReplicaSet
    from bigdl_tpu.resilience.faults import FaultInjector
    from bigdl_tpu.resilience.health import HealthPolicy

    din, n_replicas = 64, 4
    run_s = 2.5 if smoke else 6.0
    kill_after = 10 if smoke else 30  # replica-0 dispatch index floor
    model = nn.Sequential(
        nn.Linear(din, 256), nn.ReLU(), nn.Linear(256, 256), nn.ReLU(),
        nn.Linear(256, 8), nn.SoftMax())
    model.initialize(rng=0)
    spec = ((din,), np.float32)
    rng = np.random.default_rng(0)

    out = {"metric": "serving_availability_under_replica_kill",
           "unit": "fraction", "toolchain": _toolchain(),
           "config": f"mlp{din}x256x256x8/{n_replicas}replicas/"
                     f"kill_r0_after{kill_after}/run{run_s}s",
           "sweep": []}
    for n_threads in ((4,) if smoke else (4, 16)):
        plan = f"replica_death@target=0,after={kill_after},count=1"
        rs = ReplicaSet(
            model, n_replicas=n_replicas, input_spec=spec,
            max_batch_size=32, batch_timeout_ms=2.0,
            queue_capacity=4096, name=f"bench-resil{n_threads}",
            deadline_ms=5000.0, max_retries=2,
            health=HealthPolicy(probe_backoff_s=0.4),
            fault_injector=FaultInjector(plan, seed=0))
        x = rng.normal(0, 1, (1, din)).astype(np.float32)
        expected = np.asarray(rs.predict(x, timeout=30))
        counts = {"ok": 0, "shed": 0, "deadline": 0, "error": 0,
                  "wrong": 0}
        errs: list = []
        records = []  # (t_done, latency_s) of successes
        lock = _threading.Lock()
        stop_at = [0.0]
        barrier = _threading.Barrier(n_threads + 2)

        def worker():
            from bigdl_tpu.serving import (DeadlineExceeded,
                                           ServiceOverloaded)
            barrier.wait()
            while time.monotonic() < stop_at[0]:
                t0 = time.monotonic()
                try:
                    got = rs.predict(x, timeout=2.0)
                except ServiceOverloaded as e:
                    with lock:
                        counts["shed"] += 1
                    wait = e.retry_after_ms or 5.0
                    time.sleep(min(wait, 50.0) / 1e3)
                    continue
                except (DeadlineExceeded, TimeoutError):
                    with lock:
                        counts["deadline"] += 1
                    continue
                except Exception as e:  # recorded, never dropped
                    with lock:
                        counts["error"] += 1
                        errs.append(f"{type(e).__name__}: {e}")
                    continue
                t1 = time.monotonic()
                good = np.allclose(np.asarray(got), expected,
                                   rtol=1e-5, atol=1e-7)
                with lock:
                    counts["ok" if good else "wrong"] += 1
                    records.append((t1, t1 - t0))

        timeline = []  # (t, health_states) sampled by the monitor

        def monitor():
            barrier.wait()
            while time.monotonic() < stop_at[0]:
                timeline.append((time.monotonic(), rs.health_states()))
                time.sleep(0.02)

        threads = [_threading.Thread(target=worker)
                   for _ in range(n_threads)]
        threads.append(_threading.Thread(target=monitor))
        for t in threads:
            t.start()
        # stop_at must be valid BEFORE the barrier releases: workers
        # check it immediately after their own barrier.wait() returns,
        # possibly before this thread runs another statement
        stop_at[0] = time.monotonic() + run_s
        barrier.wait()
        t_start = time.monotonic()
        for t in threads:
            t.join()
        stats = rs.stats()
        rs.stop()

        # phase boundaries from the sampled health timeline
        t_dead = next((t for t, h in timeline if "quarantined" in h),
                      None)
        t_readmit = next(
            (t for t, h in timeline
             if t_dead is not None and t > t_dead
             and all(s == "healthy" for s in h)), None)

        def phase_stats(lo, hi):
            done = [(t, lat) for t, lat in records if lo <= t < hi]
            if not done or hi <= lo:
                return {"rps": 0.0, "p99_ms": None, "n": len(done)}
            lats = sorted(lat for _, lat in done)
            p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
            return {"rps": round(len(done) / (hi - lo), 1),
                    "p99_ms": round(p99 * 1e3, 2), "n": len(done)}

        t_end = stop_at[0]
        baseline = phase_stats(t_start, t_dead or t_end)
        degraded = phase_stats(t_dead or t_end, t_readmit or t_end)
        recovered = phase_stats(t_readmit or t_end, t_end)
        resil = stats["resilience"]
        point = {
            "offered_threads": n_threads,
            "counts": counts,
            "lost": 0,  # every predict() above resolved — join proves it
            "baseline": baseline,
            "degraded": degraded,
            "recovered": recovered,
            "degraded_throughput_ratio":
                round(degraded["rps"] / baseline["rps"], 3)
                if baseline["rps"] else None,
            "quarantine_s":
                round((t_readmit or t_end) - t_dead, 3)
                if t_dead is not None else None,
            "readmitted": t_readmit is not None,
            "resilience_counters": {
                k: v for k, v in sorted(resil.items())
                if isinstance(v, (int, float)) and v},
        }
        total = sum(counts.values())
        point["availability"] = (
            round(counts["ok"] / total, 4) if total else None)
        # end-of-run registry snapshot (telemetry round 2): set-level
        # resilience counters + aggregate serving view, as a /metrics
        # scrape would have seen them
        point["telemetry"] = rs.registry.scalars()
        point["aggregate"] = stats["aggregate"]
        if errs:
            point["errors"] = errs[:3]
        out["sweep"].append(point)
    avails = [p["availability"] for p in out["sweep"]
              if p["availability"] is not None]
    out["value"] = min(avails) if avails else None
    out["wrong_answers"] = sum(p["counts"]["wrong"]
                               for p in out["sweep"])
    out["all_points_readmitted"] = all(p["readmitted"]
                                       for p in out["sweep"])
    return out


def checkpoint_bench(smoke: bool = False):
    """Async-checkpointing overhead entry (the bigdl_tpu.checkpoint
    rider): the SAME training run with checkpointing async (default),
    synchronous (``checkpoint_async=False``), and disabled, reporting
    ``checkpoint_stall_fraction`` — cumulative driver-side checkpoint
    time (device→host capture + bounded enqueue) over run wall time,
    straight from the ``checkpoint/stall_fraction`` registry gauge.
    The async path must keep that fraction a small slice of the
    synchronous baseline (which pays serialize+CRC+fsync inline on the
    driver); the hard gate lives in ``tests/test_checkpoint.py``, this
    entry records the measured numbers (record-never-abort).
    """
    import tempfile

    import numpy as np

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch

    iters, every = (16, 4) if smoke else (96, 8)
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(0, 1, (64,)).astype(np.float32),
                      np.int32(rng.integers(0, 10)))
               for _ in range(512)]

    def run(mode):
        model = nn.Sequential(
            nn.Linear(64, 512), nn.ReLU(), nn.Linear(512, 512), nn.ReLU(),
            nn.Linear(512, 10), nn.LogSoftMax())
        ds = DataSet.array(samples) >> SampleToMiniBatch(64)
        opt = (optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(1e-3))
               .set_end_when(optim.max_iteration(iters)))
        # snapshots live only for the run — repeated bench invocations
        # must not accumulate orphaned checkpoint data in /tmp
        with tempfile.TemporaryDirectory(prefix="bench_ckpt_") as ckdir:
            if mode != "off":
                opt.set_checkpoint(ckdir, optim.several_iteration(every),
                                   async_save=(mode == "async"))
            t0 = time.perf_counter()
            opt.optimize()
            wall = time.perf_counter() - t0
        reg = opt.metrics.registry
        stall_g = reg.get("checkpoint/stall_fraction")
        save_h = reg.get("checkpoint/save_s")
        drv_h = reg.get("checkpoint/driver_stall_s")
        bytes_c = reg.get("checkpoint/bytes_written")
        committed = reg.get("checkpoint/snapshots_committed")
        return {
            "wall_s": round(wall, 3),
            "checkpoint_stall_fraction":
                round(stall_g.value, 5) if stall_g else 0.0,
            "driver_stall_ms_mean":
                round(drv_h.mean * 1e3, 3) if drv_h else 0.0,
            "save_ms_mean": round(save_h.mean * 1e3, 3) if save_h else 0.0,
            "snapshots": committed.value if committed else 0,
            "bytes_written": bytes_c.value if bytes_c else 0,
            # end-of-run registry snapshot (telemetry round 2)
            "telemetry": reg.scalars(),
        }

    out = {"metric": "checkpoint_stall_fraction", "unit": "fraction",
           "toolchain": _toolchain(),
           "config": f"mlp64x512x512x10/adam/batch64/iters{iters}/"
                     f"every{every}",
           "off": run("off"), "sync": run("sync"), "async": run("async")}
    out["value"] = out["async"]["checkpoint_stall_fraction"]
    out["checkpoint_stall_fraction"] = out["value"]
    out["checkpoint_stall_fraction_sync"] = \
        out["sync"]["checkpoint_stall_fraction"]
    sync_f = out["checkpoint_stall_fraction_sync"]
    out["stall_reduction_vs_sync"] = \
        round(1.0 - out["value"] / sync_f, 4) if sync_f > 0 else None
    return out


def elastic_child():
    """``--elastic-child``: one elastic training run on an 8-device
    virtual CPU mesh — world 4, a seeded ``resize@`` shrink to 2
    mid-run, resume from the boundary snapshot with the ZeRO-1 state
    re-sharded, then a regrow back to 4 (``bigdl_tpu.resilience.
    membership``).  Prints the measured JSON: membership epochs,
    ``resilience/resize_downtime_s`` / ``steps_lost_to_resize``
    straight from the registry, and median per-step time split into
    baseline (world 4) / degraded (world 2) / recovered (world 4)
    segments, each segment dropping its boundary step so the restore +
    recompile gap lands in the downtime number, not the throughput."""
    import tempfile

    import numpy as np
    import jax
    from jax.sharding import Mesh

    from bigdl_tpu import nn, optim
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.utils.config import configure, reset_config

    smoke = os.environ.get("_BENCH_ELASTIC_SMOKE") == "1"
    iters, shrink_at, regrow_at, every = \
        (18, 6, 12, 2) if smoke else (60, 20, 40, 4)
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(0, 1, (64,)).astype(np.float32),
                      np.int32(rng.integers(0, 10)))
               for _ in range(2048)]

    step_t = {}  # neval -> wall clock at replay (last write wins)

    class _Summary:
        def add_train_step(self, step, loss, lr, throughput):
            step_t[step] = time.perf_counter()

        def add_scalar(self, *a):
            pass

        def trigger_for(self, name):
            return None

    model = nn.Sequential(
        nn.Linear(64, 256), nn.ReLU(), nn.Linear(256, 256), nn.ReLU(),
        nn.Linear(256, 10), nn.LogSoftMax())
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    plan = f"resize@at={shrink_at},to=2;resize@at={regrow_at},to=4"
    configure(fault_plan=plan)
    try:
        # snapshots live only for the run — repeated bench invocations
        # must not accumulate orphaned checkpoint data in /tmp
        with tempfile.TemporaryDirectory(prefix="bench_elastic_") as d:
            opt = (optim.DistriOptimizer(
                model, DataSet.array(samples) >> SampleToMiniBatch(32),
                nn.ClassNLLCriterion(), mesh=mesh)
                .set_optim_method(optim.SGD(learning_rate=0.05))
                .set_seed(0)
                .set_train_summary(_Summary())
                .set_end_when(optim.max_iteration(iters)))
            opt.set_checkpoint(d, optim.several_iteration(every))
            t0 = time.perf_counter()
            opt.optimize()  # zero aborted runs IS the acceptance shape
            wall = time.perf_counter() - t0
    finally:
        reset_config()

    def seg_ms(lo, hi):
        # median inter-step ms over (lo, hi]; the boundary step lo+1
        # is excluded so the restore/recompile gap stays out
        ts = [step_t[s] for s in sorted(step_t) if lo + 1 < s <= hi]
        if len(ts) < 2:
            return None
        deltas = sorted(b - a for a, b in zip(ts, ts[1:]))
        return round(deltas[len(deltas) // 2] * 1e3, 2)

    snap = opt.metrics.registry.snapshot()
    hist = snap["histograms"].get("resilience/resize_downtime_s") or {}
    baseline = seg_ms(0, shrink_at)
    degraded = seg_ms(shrink_at, regrow_at)
    recovered = seg_ms(regrow_at, iters)
    return {
        "config": f"mlp64x256x256x10/sgd/batch32/iters{iters}/"
                  f"shrink4to2@{shrink_at}/regrow@{regrow_at}/"
                  f"ckpt_every{every}",
        "wall_s": round(wall, 3),
        "iterations": int(opt.state["neval"]),
        "membership_epoch": int(snap["gauges"].get(
            "resilience/membership_epoch", 0)),
        "worlds": [e.world for e in opt._membership.history()],
        "resize_downtime_s": {
            k: round(hist[k], 4) for k in ("count", "mean", "max", "sum")
            if k in hist},
        "steps_lost_to_resize": snap["counters"].get(
            "resilience/steps_lost_to_resize", 0),
        "step_ms": {"baseline_world4": baseline,
                    "degraded_world2": degraded,
                    "recovered_world4": recovered},
        "recovered_throughput_ratio":
            round(baseline / recovered, 3)
            if baseline and recovered else None,
        # end-of-run registry snapshot (telemetry round 2)
        "telemetry": opt.metrics.registry.scalars(),
    }


def elastic_bench(smoke: bool = False):
    """Elastic-training entry (``--elastic``, the ISSUE-16 rider): a
    child on an 8-device virtual CPU mesh runs a full shrink/regrow
    cycle (world 4 → 2 → 4 via seeded ``resize@`` clauses) and this
    wrapper records the measured resize downtime, steps lost, and the
    recovered-throughput ratio.  The correctness gates — bitwise
    resume at the replay boundary, ``membership_epoch`` == 3, zero
    aborted runs — live in ``tests/test_membership.py``; this entry
    records the numbers (record-never-abort: a failed child is an
    error string in the capture, never a crash)."""
    out = {"metric": "elastic_resize_downtime_s", "unit": "seconds",
           "toolchain": _toolchain()}
    r = subprocess_run(
        [sys.executable, __file__, "--elastic-child"],
        env=_cpu_mesh_env(_BENCH_ELASTIC_SMOKE="1" if smoke else "0"),
        parse=json.loads)
    if not isinstance(r, dict):
        out["error"] = "elastic child failed"
        out["value"] = None
        return out
    out.update(r)
    out["value"] = (r.get("resize_downtime_s") or {}).get("mean")
    out["zero_aborted_runs"] = r.get("membership_epoch") == 3 \
        and r.get("worlds") == [4, 2, 4]
    return out


if __name__ == "__main__":
    if "--scaling-child" in sys.argv:
        scaling_child()
    elif "--collective-child" in sys.argv:
        collective_child()
    elif "--elastic-child" in sys.argv:
        print(json.dumps(elastic_child()))
    elif "--serving" in sys.argv:
        print(json.dumps(serving_bench("--smoke" in sys.argv)))
    elif "--checkpoint" in sys.argv:
        print(json.dumps(checkpoint_bench("--smoke" in sys.argv)))
    elif "--resilience" in sys.argv:
        print(json.dumps(resilience_bench("--smoke" in sys.argv)))
    elif "--elastic" in sys.argv:
        print(json.dumps(elastic_bench("--smoke" in sys.argv)))
    else:
        main(sys.argv[1:])
