"""Benchmark driver — prints ONE JSON line.

Analog of the reference's throughput harness
``DL/models/utils/DistriOptimizerPerf.scala:56-140`` (synthetic-input
records/sec).  Measures BOTH BASELINE.json models — ResNet-50 and
Inception-v1 — as full ImageNet training steps (fwd+bwd+SGD-momentum
update) on the local TPU chip: images/sec/chip.

Config: NHWC, bf16 compute / f32 master params, batch 256, donated
buffers — best of the layout×batch×remat sweep on v5e (see git
history; batch 512 regresses ~6% past its own bandwidth floor from
memory pressure, FULL per-block remat costs ~20% because recomputed
convs re-read activations — the "tails" variant that saves conv
outputs and recomputes only BN/ReLU is selected per measurement).

Variance discipline (round-4): the reported value is the MEDIAN over
``windows`` independent timing windows (fresh compile excluded), with
the min/max/relative spread attached, so a ±3% wobble can be told from
a real regression.  Round-3's best-of-4 could not.

``bottleneck`` is TRACE-BACKED, not asserted: XLA's compiled-executable
cost analysis (flops + bytes accessed) gives the MXU-time and HBM-time
floors; the measured step time is compared against both — for BOTH
models since round 4.

``mfu`` uses the XLA-counted flops of the compiled step (not a paper
constant) over the 197 TFLOP/s v5e bf16 peak.  XLA counts 2 flops per
MAC — the same convention as the 197 TFLOP/s spec.

``scaling_efficiency`` (round-4, always emitted): fixed-global-batch
SPMD partitioning overhead on a 1-vs-8 virtual CPU mesh (the only
standing proxy this single-chip environment can produce for the
BASELINE ">60% efficiency 1→32 chips" claim; reference
``docs/docs/whitepaper.md:160-164``).  Gate: ≥0.6 at 8 devices.

Round-4 experiment log (all medians over ≥5 windows, v5e, batch 256;
baseline ResNet-50 2499.7 img/s / 78.7 GB/step, Inception-v1 4645 /
37.3 GB/step):
- remat="tails" (save conv outputs, recompute BN/ReLU): 2160 img/s,
  bytes 92.5 GB — XLA's own saved-residual choice already beats the
  forced policy, and checkpoint boundaries block cross-block fusion.
- full per-block remat: ~20% slower (r3).
- batch 384: 2442 img/s, floor-fraction drops 0.94→0.84 (memory
  pressure); batch 512 worse still (r2).
- bf16 stochastic-rounded momentum: 2443 img/s, bytes 79.5 GB —
  optimizer state is 0.26% of step traffic; the SR noise costs more
  than it saves.  Kept as a memory-capacity option (SGD state_dtype).
- maxpool backward (select-and-scatter) replacements: ablations show
  S&S wastes ~8.6 ms/step on Inception (pool-stubbed model runs at
  96.8% of its floor vs 82.6% real), but every alternative loses more:
  XLA phase decomposition 67.8 GB, pallas first-match kernel 80.4 GB
  (layout copies: pallas can't accept XLA's batch-minor layouts),
  hand-written custom-vjp 95.9 GB.  See nn/layers.py SpatialMaxPooling
  and ops/pallas_pool.py.
- Inception MFU ceiling: at its own HBM floor (45.5 ms) MFU caps at
  0.254, so the 0.28 target is unreachable without removing bytes the
  model actually moves; measured 0.21 = 83% of that roofline, with the
  S&S waste above accounting for most of the residual gap.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

import numpy as np

# round-1 recorded TPU v5 lite measurement (bf16, NCHW, batch 64); later
# rounds report improvement vs this anchor
BASELINE_IMAGES_PER_SEC = 1945.9  # 2026-07-29 r01
PEAK_BF16_FLOPS = 197e12          # v5e MXU peak
HBM_BYTES_PER_SEC = 819e9         # v5e HBM bandwidth


def _measure(model, batch: int, windows: int = 6, iters: int = 32):
    """Compile + run one training step; return (per-window img/s list,
    cost-analysis dict)."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    from bigdl_tpu import nn, optim
    from bigdl_tpu.utils.precision import mixed_precision_loss_fn

    criterion = nn.ClassNLLCriterion()
    method = optim.SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)
    params, mstate = model.init(jax.random.PRNGKey(0))
    ostate = method.init_state(params)
    x = jnp.asarray(np.random.default_rng(0).normal(
        0, 1, (batch, 224, 224, 3)).astype(np.float32))
    y = jnp.asarray(np.random.default_rng(1).integers(
        0, 1000, (batch,)).astype(np.int32))

    base_loss = mixed_precision_loss_fn(model, criterion, jnp.bfloat16)
    grad_fn = jax.value_and_grad(base_loss, has_aux=True)
    rng0 = jax.random.PRNGKey(42)  # dropout rng (Inception-v1 trains one)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(p, ms, os_, x, y, lr, it, rng):
        (loss, ms), g = grad_fn(p, ms, x, y, rng)
        p, os_ = method.update(g, p, os_, lr, it)
        return p, ms, os_, loss

    # ONE compile: the AOT executable serves both cost_analysis and the
    # timing loop (a separate jit dispatch would compile a second time)
    ca = {}
    run = step
    try:
        compiled = step.lower(params, mstate, ostate, x, y, 0.1, 0,
                              rng0).compile()
        c = compiled.cost_analysis()
        if isinstance(c, list):
            c = c[0]
        ca = {"flops": float(c.get("flops", 0.0)),
              "bytes": float(c.get("bytes accessed", 0.0))}
        run = compiled
    except Exception:
        pass

    # warmup.  NOTE: on the experimental 'axon' TPU platform
    # block_until_ready does not actually wait for completion — a host
    # round-trip (float()) is the only reliable sync.
    params, mstate, ostate, loss = run(params, mstate, ostate, x, y,
                                       np.float32(0.1), np.int32(0), rng0)
    float(loss)

    samples = []
    for w in range(windows):
        t0 = time.perf_counter()
        for i in range(iters):
            params, mstate, ostate, loss = run(
                params, mstate, ostate, x, y, np.float32(0.1),
                np.int32(w * iters + i), rng0)
        float(loss)  # full pipeline sync
        samples.append(batch * iters / (time.perf_counter() - t0))
    return samples, ca


def _stats(samples):
    med = statistics.median(samples)
    return med, {
        "median": round(med, 1),
        "min": round(min(samples), 1),
        "max": round(max(samples), 1),
        "rel_spread": round((max(samples) - min(samples)) / med, 4),
        "windows": len(samples),
    }


def _bottleneck(ca, ips, batch):
    """Roofline comparison of the measured step vs the compiled
    executable's XLA-counted flop and byte floors."""
    step_ms = batch / ips * 1e3
    t_mxu = ca["flops"] / PEAK_BF16_FLOPS * 1e3
    t_hbm = ca["bytes"] / HBM_BYTES_PER_SEC * 1e3
    return {
        "kind": "hbm" if t_hbm > t_mxu else "mxu",
        "xla_flops_G": round(ca["flops"] / 1e9, 1),
        "xla_bytes_GB": round(ca["bytes"] / 1e9, 2),
        "t_mxu_floor_ms": round(t_mxu, 2),
        "t_hbm_floor_ms": round(t_hbm, 2),
        "t_measured_ms": round(step_ms, 2),
        "hbm_floor_fraction": round(t_hbm / step_ms, 3),
    }


def _scaling_efficiency():
    """1-vs-8 virtual-CPU-mesh partitioning overhead (see module doc).
    Subprocess-isolated so the TPU backend in this process is
    untouched."""
    results = {}
    for n in (1, 8):
        env = dict(os.environ)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count=8")
        env["XLA_FLAGS"] = " ".join(flags)
        env["_BENCH_SCALING_N"] = str(n)
        out = subprocess_run([sys.executable, __file__, "--scaling-child"],
                             env=env)
        if out is None:
            return None
        results[n] = out
    return {
        "value": round(results[8] / results[1], 3),
        "images_per_sec": {str(n): round(v, 1)
                           for n, v in results.items()},
    }


def subprocess_run(cmd, env):
    import subprocess
    out = subprocess.run(cmd, env=env, capture_output=True, text=True)
    if out.returncode != 0:
        print(out.stderr[-2000:], file=sys.stderr)
        return None
    return float(out.stdout.strip().splitlines()[-1])


def main(argv):
    from bigdl_tpu.models.resnet import resnet50
    from bigdl_tpu.models.inception import inception_v1

    batch = 256
    remat = "tails" if "--remat-tails" in argv else (
        True if "--remat-full" in argv else False)
    r_samples, r_ca = _measure(resnet50(format="NHWC", remat=remat), batch)
    r_ips, r_spread = _stats(r_samples)
    if "--resnet-only" in argv:
        out = {"metric": "resnet50_train_images_per_sec_per_chip",
               "value": round(r_ips, 1), "spread": r_spread,
               "remat": str(remat)}
        if r_ca:
            out["bottleneck"] = _bottleneck(r_ca, r_ips, batch)
        print(json.dumps(out))
        return
    i_samples, i_ca = _measure(inception_v1(format="NHWC"), batch)
    i_ips, i_spread = _stats(i_samples)

    out = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(r_ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(r_ips / BASELINE_IMAGES_PER_SEC, 3),
        "spread": r_spread,
        "inception_v1_images_per_sec_per_chip": round(i_ips, 1),
        "inception_spread": i_spread,
        "config": f"NHWC/bf16/batch{batch}/donated"
                  + (f"/remat-{remat}" if remat else ""),
    }
    if r_ca:
        out["mfu"] = round(r_ips * (r_ca["flops"] / batch)
                           / PEAK_BF16_FLOPS, 4)
        out["bottleneck"] = _bottleneck(r_ca, r_ips, batch)
    if i_ca:
        out["inception_mfu"] = round(i_ips * (i_ca["flops"] / batch)
                                     / PEAK_BF16_FLOPS, 4)
        out["inception_bottleneck"] = _bottleneck(i_ca, i_ips, batch)
    sc = _scaling_efficiency()
    if sc is not None:
        out["scaling_efficiency"] = sc["value"]
        out["scaling_detail"] = sc["images_per_sec"]
        out["scaling_gate_0p6"] = "pass" if sc["value"] >= 0.6 else "FAIL"
    else:
        # a crashed child must read as a failed gate, not a missing key
        out["scaling_efficiency"] = None
        out["scaling_gate_0p6"] = "FAIL"
        out["scaling_error"] = "scaling child subprocess failed"
    print(json.dumps(out))


def scaling():
    """Standalone scaling mode (same measurement the main entry embeds).

    True multi-chip weak scaling cannot be measured on one host: the 8
    virtual devices share the same physical cores, so contention would
    masquerade as scaling loss.  What CAN be isolated is the overhead the
    SPMD partitioning itself adds: run the SAME global problem (fixed
    global batch) unsharded on 1 device vs sharded over 8 — identical
    total CPU work, so efficiency = t(1-dev)/t(8-dev) ≈ 1 - collective/
    partition overhead.  The real 1→32-chip ICI measurement (BASELINE
    north star >60%) needs pod hardware the driver doesn't provide."""
    sc = _scaling_efficiency()
    if sc is None:
        raise RuntimeError("scaling child failed")
    print(json.dumps({
        "metric": "resnet_cifar_sharding_overhead_efficiency_cpu_mesh",
        "value": sc["value"],
        "unit": "parallel_efficiency",
        "images_per_sec": sc["images_per_sec"],
    }))


def scaling_child():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from bigdl_tpu import nn, optim
    from bigdl_tpu.models.resnet import resnet_cifar

    n = int(os.environ["_BENCH_SCALING_N"])
    devs = jax.devices()
    assert len(devs) >= n, (n, devs)
    mesh = Mesh(np.array(devs[:n]), ("data",))

    model = resnet_cifar(depth=20)
    criterion = nn.ClassNLLCriterion()
    method = optim.SGD(learning_rate=0.1, momentum=0.9)
    params, mstate = model.init(jax.random.PRNGKey(0))
    ostate = method.init_state(params)
    batch = 128  # FIXED global batch: same total work for every n
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, 3, 32, 32)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, (batch,)).astype(np.int32))
    data_sh = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())
    x = jax.device_put(x, data_sh)
    y = jax.device_put(y, data_sh)
    params = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), params)
    mstate = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), mstate)
    ostate = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), ostate)

    def loss_fn(p, ms, x, y):
        out, ms2 = model.apply(p, ms, x, training=True)
        return criterion.apply(out, y), ms2

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(p, ms, os_, x, y, it):
        (loss, ms), g = grad_fn(p, ms, x, y)
        p, os_ = method.update(g, p, os_, 0.1, it)
        return p, ms, os_, loss

    params, mstate, ostate, loss = step(params, mstate, ostate, x, y, 0)
    loss.block_until_ready()
    iters = 10
    t0 = time.perf_counter()
    for i in range(iters):
        params, mstate, ostate, loss = step(params, mstate, ostate, x, y, i)
    loss.block_until_ready()
    dt = time.perf_counter() - t0
    print(batch * iters / dt)


if __name__ == "__main__":
    if "--scaling-child" in sys.argv:
        scaling_child()
    elif "--scaling" in sys.argv:
        scaling()
    else:
        main(sys.argv[1:])
